"""Generic jitted train-step factory shared by the workload entrypoints."""

from __future__ import annotations

import time
from typing import Any, Callable, Optional, Tuple

import jax
import optax


def record_step_telemetry(steps: int, duration_s: float,
                          examples_per_step: int = 0,
                          registry=None) -> None:
    """Publish a training run's step-time/throughput on the obs registry.

    The scan-based trainers execute the whole run as ONE compiled program,
    so per-step timing does not exist host-side; what is recorded is the
    run's mean step time (one histogram observation per run) plus
    cumulative step/example counters and an examples-per-second gauge —
    the numbers future perf PRs cite from ``GET /metrics``."""
    from ..obs.metrics import REGISTRY

    reg = registry or REGISTRY
    if steps <= 0 or duration_s < 0:
        return
    reg.histogram(
        "kctpu_trainer_step_duration_seconds",
        "Mean per-step train time of a completed run (one observation per run)",
    ).observe(duration_s / steps)
    reg.histogram(
        "kctpu_trainer_fit_duration_seconds",
        "Whole-run compiled-train-program wall time",
    ).observe(duration_s)
    reg.counter("kctpu_trainer_steps_total",
                "Training steps completed").inc(steps)
    if examples_per_step > 0:
        reg.counter("kctpu_trainer_examples_total",
                    "Training examples consumed").inc(steps * examples_per_step)
        if duration_s > 0:
            reg.gauge("kctpu_trainer_examples_per_second",
                      "Throughput of the most recent completed run").set(
                steps * examples_per_step / duration_s)


def make_train_step(
    loss_fn: Callable[[Any, Any], jax.Array],
    optimizer: optax.GradientTransformation,
) -> Callable:
    """Returns jitted ``step(params, opt_state, batch) -> (params, opt_state,
    loss)`` with donated carries so buffers update in place on TPU."""

    def step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return jax.jit(step, donate_argnums=(0, 1))


def train_scan_stateful(
    loss_fn: Callable[[Any, Any, Any], Tuple[jax.Array, Any]],
    optimizer: optax.GradientTransformation,
    params: Any,
    opt_state: Any,
    state: Any,
    batches: Any,
) -> Tuple[Any, Any, Any, jax.Array]:
    """Whole training loop as ONE jitted ``lax.scan`` over stacked batches —
    a single dispatch instead of one per step, which matters enormously for
    small models where per-step Python/dispatch overhead rivals the math.

    ``loss_fn(params, batch, state) -> (loss, new_state)`` threads mutable
    model state (e.g. BatchNorm statistics) through the scan.
    Returns (params, state, opt_state, last_loss)."""

    def body(carry, batch):
        p, st, s = carry
        (loss, st), grads = jax.value_and_grad(loss_fn, has_aux=True)(p, batch, st)
        updates, s = optimizer.update(grads, s, p)
        p = optax.apply_updates(p, updates)
        return (p, st, s), loss

    @jax.jit
    def run(p, st, s, batches):
        (p, st, s), losses = jax.lax.scan(body, (p, st, s), batches)
        return p, st, s, losses[-1]

    return run(params, state, opt_state, batches)


def train_scan(
    loss_fn: Callable[[Any, Any], jax.Array],
    optimizer: optax.GradientTransformation,
    params: Any,
    opt_state: Any,
    batches: Any,
) -> Tuple[Any, Any, jax.Array]:
    """Stateless variant of :func:`train_scan_stateful`.
    Returns (params, opt_state, last_loss)."""
    params, _, opt_state, loss = train_scan_stateful(
        lambda p, b, st: (loss_fn(p, b), st),
        optimizer, params, opt_state, None, batches,
    )
    return params, opt_state, loss


def train_scan_dist(
    loss_fn: Callable[[Any, Any], jax.Array],
    optimizer: optax.GradientTransformation,
    params: Any,
    opt_state: Any,
    steps: int,
    mesh,
    axis: str,
    local_batches_fn: Callable[[jax.Array], Any],
    eval_counts_fn: Optional[Callable[[Any, jax.Array], Tuple[jax.Array, jax.Array]]] = None,
    aot_cache: Optional[str] = None,
    examples_per_step: int = 0,
):
    """Distributed data-parallel training as ONE compiled program with ONE
    collective per step.

    The reference's PS data plane ships every gradient tensor to the PS
    each step (ref: examples/workdir/mnist_replica.py:251-264 — one grpc
    round-trip per variable).  The naive SPMD re-expression inherits that
    shape: XLA inserts one all-reduce per gradient leaf, and on a
    process-per-worker gang each collective costs fixed rendezvous latency
    regardless of payload size (measured: ~3.7ms/call for 8 floats or 160k
    floats alike — docs/PERF.md).  So the whole step's cross-worker traffic
    is flattened into a single psum: gradients ravel into one flat buffer,
    the scalar loss rides in the same buffer, and eval reduces through one
    more psum at the end.  Latency-bound collectives make "how many", not
    "how big", the cost model.

    Everything else lives inside the same jit under ``shard_map``:

    - ``local_batches_fn(shard_index) -> batches`` builds this shard's
      slice of every global batch ON DEVICE (leading dims
      ``[steps_per_epoch, local_bs, ...]``); the scan cycles over the epoch
      axis, so a "dataset" is revisited exactly like a host-staged one but
      costs no host generation, no host->device copy, and no global-array
      assembly consensus.
    - ``eval_counts_fn(params, shard_index) -> (num, den)`` returns this
      shard's contribution to a global ratio metric (e.g. correct count,
      example count); the psum'd ratio comes back as the final output.

    ``aot_cache`` (a file path) opts into ahead-of-time executable reuse:
    on miss the compiled executable is serialized there
    (``jax.experimental.serialize_executable``), on hit it is loaded and
    run directly — skipping trace/lower/compile entirely.  On a one-core
    host every process's Python jit pipeline serializes with every other
    process's, and a peer stuck compiling makes its partners burn the core
    spinning in the collective rendezvous, so skipping the pipeline is
    worth more than a warm HLO cache (measured: ~4.4s of per-call overhead
    -> ~0.35s, docs/PERF.md).  The path must be per-process and
    per-program-config (callers embed process index and shape-affecting
    args); a stale or unreadable file falls back to the compile path.

    Returns ``(params, opt_state, last_loss[, metric])``.
    """
    import jax.numpy as jnp
    from jax.flatten_util import ravel_pytree
    from jax.sharding import PartitionSpec as P

    from ..parallel.compat import pvary, shard_map

    dp = mesh.shape[axis]

    def inner(params, opt_state):
        i = jax.lax.axis_index(axis)
        batches = local_batches_fn(i)
        spe = jax.tree_util.tree_leaves(batches)[0].shape[0]

        def body(carry, t):
            p, s = carry
            b = jax.tree_util.tree_map(
                lambda a: jax.lax.dynamic_index_in_dim(
                    a, t % spe, axis=0, keepdims=False),
                batches)
            # Differentiate w.r.t. a VARYING view of the params: grads of
            # replicated params would get an automatic per-tensor psum
            # inserted in the transpose (one hidden collective per gradient
            # leaf — the exact per-variable shape this function exists to
            # avoid); pvary keeps the local grads local so the one explicit
            # flat psum below is the step's only collective.
            pv = jax.tree_util.tree_map(lambda a: pvary(a, axis), p)
            loss, grads = jax.value_and_grad(loss_fn)(pv, b)
            flat, unravel = ravel_pytree(grads)
            # One latency-bound collective for the whole step: grads + loss.
            flat = jax.lax.psum(
                jnp.concatenate([flat, loss[None].astype(flat.dtype)]), axis) / dp
            updates, s = optimizer.update(unravel(flat[:-1]), s, p)
            p = optax.apply_updates(p, updates)
            return (p, s), flat[-1]

        (params, opt_state), losses = jax.lax.scan(
            body, (params, opt_state), jnp.arange(steps, dtype=jnp.int32))
        out = (params, opt_state, losses[-1])
        if eval_counts_fn is not None:
            num, den = eval_counts_fn(params, i)
            nd = jax.lax.psum(
                jnp.stack([jnp.asarray(num, jnp.float32),
                           jnp.asarray(den, jnp.float32)]), axis)
            out = out + (nd[0] / nd[1],)
        return out

    fit = jax.jit(
        shard_map(inner, mesh=mesh, in_specs=(P(), P()), out_specs=P()),
        donate_argnums=(0, 1),
    )

    def _timed(run: Callable[[], Any], cache: str) -> Any:
        # One span + one telemetry record for the whole compiled run (the
        # scan is one dispatch; block_until_ready so the measured time is
        # execution, not dispatch — callers consume the outputs right away).
        from ..obs.trace import span as obs_span
        from .progress import reporter

        # Heartbeats for the opaque compiled-run window: the scan is one
        # dispatch, so a keepalive thread re-publishes liveness until the
        # program returns — then the final beat carries the real step
        # count, throughput, and loss.
        rep = reporter()
        rep.beat(phase="fit",
                 compile_source={"hit": "cache-hit", "miss": "compiled"}.get(cache, ""))
        rep.start_keepalive()
        try:
            with obs_span("trainer/fit", steps=steps,
                          aot_cache=cache) as sp:
                out = jax.block_until_ready(run())
                sp.args["process"] = jax.process_index()
        finally:
            rep.stop_keepalive()
        dur = sp.dur if sp.dur else 0.0
        record_step_telemetry(steps, dur, examples_per_step)
        try:
            final_loss = float(out[2])
        except (TypeError, IndexError, ValueError):
            final_loss = None
        rep.beat(step=steps, loss=final_loss, phase="fit",
                 examples_per_sec=(steps * examples_per_step / dur
                                   if dur > 0 and examples_per_step else None))
        return out

    from .compile_cache import aot_supported

    if aot_cache and aot_supported():
        import time as _time

        from ..obs.trace import span as obs_span
        from .compile_cache import (
            load_executable,
            observe_compile,
            store_executable,
        )
        from .progress import reporter as _reporter

        t0 = _time.perf_counter()
        loaded = load_executable(aot_cache)
        if loaded is not None:
            observe_compile("cache-hit", _time.perf_counter() - t0)
            return _timed(lambda: loaded(params, opt_state), "hit")
        # A long compile looks exactly like a frozen-step stall from the
        # controller: beat phase="compile" with a keepalive for the
        # duration (the stall detector holds its step deadline for it).
        with _reporter().compiling(), obs_span("workload/compile",
                                               what="fit") as sp:
            compiled = fit.trace(params, opt_state).lower().compile()
            sp.args["source"] = "compiled"
        observe_compile("compiled", _time.perf_counter() - t0)
        store_executable(aot_cache, compiled)
        return _timed(lambda: compiled(params, opt_state), "miss")
    return _timed(lambda: fit(params, opt_state), "off")


def make_dist_step(
    loss_fn: Callable[[Any, Any], jax.Array],
    optimizer: optax.GradientTransformation,
    mesh,
    axis: str,
    donate: bool = True,
):
    """One jitted distributed train step — the TTFS pipeline's unit of
    compilation.

    ``step(params, opt_state, x_all, y_all, t) -> (params, opt_state,
    loss)``: the whole stacked dataset (``[n_steps, global_bs, ...]``,
    batch dim sharded over ``axis``) stays resident on device and the step
    indexes batch ``t % n_steps`` itself, so the host loop dispatches ONE
    program per step with no per-step staging and no per-index recompiles.
    Same collective shape as :func:`train_scan_dist`'s scan body — grads
    and loss ride one flat psum — and params/opt_state are donated, so
    buffers update in place.

    Per-step dispatch costs more than the scan for a whole fixed-length
    run, but it is what makes the first step (and every step) OBSERVABLE
    host-side — the progress plane gets real per-step beats instead of a
    keepalive — and what lets the executable be AOT-compiled from abstract
    shapes alone, before the training data exists
    (compile_cache.aot_compile overlaps host setup).

    ``donate=False`` trades the in-place carry update for a per-step
    params/opt_state copy (~ms at MLP scale): deserialized executables on
    older jaxlib mishandle donated aliasing (heap corruption —
    compile_cache.aot_supported), so the donation-free form is what makes
    SERIALIZED multi-process executables safe there."""
    import jax.numpy as jnp
    from jax.flatten_util import ravel_pytree
    from jax.sharding import PartitionSpec as P

    from ..parallel.compat import pvary, shard_map

    dp = mesh.shape[axis]

    def inner(params, opt_state, x_all, y_all, t):
        n = x_all.shape[0]
        b = jax.tree_util.tree_map(
            lambda a: jax.lax.dynamic_index_in_dim(
                a, jax.lax.rem(t, jnp.int32(n)), axis=0, keepdims=False),
            (x_all, y_all))
        # Varying view of the replicated params: keeps grads local so the
        # explicit flat psum below is the step's only collective (see
        # train_scan_dist).
        pv = jax.tree_util.tree_map(lambda a: pvary(a, axis), params)
        loss, grads = jax.value_and_grad(loss_fn)(pv, b)
        flat, unravel = ravel_pytree(grads)
        flat = jax.lax.psum(
            jnp.concatenate([flat, loss[None].astype(flat.dtype)]), axis) / dp
        updates, opt_state = optimizer.update(unravel(flat[:-1]), opt_state,
                                              params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, flat[-1]

    return jax.jit(
        shard_map(inner, mesh=mesh,
                  in_specs=(P(), P(), P(None, axis), P(None, axis), P()),
                  out_specs=(P(), P(), P())),
        donate_argnums=(0, 1) if donate else (),
    )


def train_step_loop_dist(
    step: Callable,
    params: Any,
    opt_state: Any,
    x_all: Any,
    y_all: Any,
    steps: int,
    examples_per_step: int = 0,
    compile_source: str = "",
    beat_interval_s: float = 0.25,
    start_step: int = 0,
    checkpoint_every: int = 0,
    checkpoint_fn: Optional[Callable[[int, Any, Any], None]] = None,
) -> Tuple[Any, Any, jax.Array]:
    """Drive a (usually AOT-precompiled) :func:`make_dist_step` executable
    from ``start_step`` to ``steps`` with REAL per-step progress.

    The first step is special — it is the end of the time-to-first-step
    pipeline: it gets its own ``workload/first_step`` span and an
    immediate beat carrying ``compile_source`` ("cache-hit" vs
    "compiled"), so the controller's progress plane records both when
    training actually started and whether the compile was paid or skipped.
    Subsequent steps beat at most every ``beat_interval_s`` (a float(loss)
    sync per beat; per-step syncing would serialize host and device).

    Recovery hooks (the kill→restore→resume loop, docs/RECOVERY.md):

    - ``start_step`` > 0 resumes a restored run — the loop executes steps
      ``start_step..steps-1`` and the first beat carries
      ``resumed_from_step`` so the controller's progress plane knows a
      backward-jumping step counter is a resume (phase="restore" hold in
      the stall detector), and the chaos bench can compute lost steps;
    - ``checkpoint_fn(done_steps, params, opt_state)`` runs every
      ``checkpoint_every`` completed steps (callers pass an ASYNC
      CheckpointManager.save so the write overlaps the next steps) —
      bounding the steps a kill can lose to the interval.

    Returns (params, opt_state, last_loss)."""
    import time as _time

    import numpy as np

    from ..obs.trace import span as obs_span
    from .progress import reporter

    # A restore at (or past) the finish line re-runs the last step: the
    # loop keeps a well-defined loss and the final checkpoint/telemetry
    # shape, at the cost of one redundant step.
    start_step = max(0, min(start_step, steps - 1))
    run_steps = steps - start_step
    rep = reporter()
    t0 = _time.perf_counter()
    with obs_span("workload/first_step", start_step=start_step) as sp_first:
        params, opt_state, loss = step(params, opt_state, x_all, y_all,
                                       np.int32(start_step))
        loss = jax.block_until_ready(loss)
        sp_first.args["process"] = jax.process_index()
    rep.beat(step=start_step + 1, loss=float(loss), phase="fit",
             compile_source=compile_source,
             resumed_from_step=start_step if start_step else None,
             examples_per_sec=(examples_per_step / sp_first.dur
                               if sp_first.dur > 0 and examples_per_step
                               else None))
    next_beat = _time.perf_counter() + beat_interval_s
    with obs_span("workload/fit", steps=steps,
                  start_step=start_step) as sp_fit:
        for t in range(start_step + 1, steps):
            params, opt_state, loss = step(params, opt_state, x_all, y_all,
                                           np.int32(t))
            done = t + 1
            if (checkpoint_fn is not None and checkpoint_every > 0
                    and done % checkpoint_every == 0 and done < steps):
                # Async save: Orbax serializes in the background, the next
                # step overlaps the write; a step becomes restorable only
                # once finalized (checkpoint.py), so a kill mid-save falls
                # back to the previous interval.
                checkpoint_fn(done, params, opt_state)
            now = _time.perf_counter()
            if now >= next_beat:
                next_beat = now + beat_interval_s
                rep.beat(step=done, loss=float(loss),
                         examples_per_sec=((done - start_step)
                                           * examples_per_step / (now - t0)
                                           if examples_per_step else None))
        loss = jax.block_until_ready(loss)
    dur = sp_first.dur + sp_fit.dur
    record_step_telemetry(run_steps, dur, examples_per_step)
    rep.beat(step=steps, loss=float(loss), phase="fit",
             examples_per_sec=(run_steps * examples_per_step / dur
                               if dur > 0 and examples_per_step else None))
    return params, opt_state, loss


def replicate_pytree(mesh, tree):
    """Every-leaf-replicated global arrays from host-identical pytrees
    (the multi-process-safe ``device_put`` for params/opt_state — every
    process passes bitwise-identical host values)."""
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    sharding = NamedSharding(mesh, P())
    if jax.process_count() == 1:
        return jax.tree_util.tree_map(
            lambda a: jax.device_put(a, sharding), tree)
    return jax.tree_util.tree_map(
        lambda a: jax.make_array_from_process_local_data(
            sharding, np.asarray(a), np.asarray(a).shape), tree)


def batch_stack(x: jax.Array, y: jax.Array, steps: int, batch_size: int):
    """[n,...] data -> ([steps, bs, ...], [steps, bs]) cycling over n."""
    import jax.numpy as jnp

    n = x.shape[0]
    idx = (jnp.arange(steps)[:, None] * batch_size + jnp.arange(batch_size)[None, :]) % n
    return x[idx], y[idx]


def global_batches(mesh, axis: str, arrays, global_batch: int):
    """Host-local stacked batches -> ONE global array per input, steps
    unsharded and the batch dim sharded over ``axis``.

    Single-process: a plain device_put.  Multi-process (classic Worker gangs
    and multi-host TPU slices): each process contributes its
    ``global_batch / process_count`` rows of every step's batch
    (``jax.make_array_from_process_local_data``), so the scan trains one
    shared model over the union of the workers' shards — the all-reduce
    re-expression of the reference's PS data plane, not N private runs.
    """
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    sharding = NamedSharding(mesh, P(None, axis))
    if jax.process_count() == 1:
        return tuple(jax.device_put(a, sharding) for a in arrays)
    out = []
    for a in arrays:
        gshape = (a.shape[0], global_batch) + tuple(a.shape[2:])
        out.append(jax.make_array_from_process_local_data(
            sharding, np.asarray(a), gshape))
    return tuple(out)


def replicate_global(mesh, *arrays):
    """Fully-replicated global arrays (every process passes identical data;
    used for eval sets so accuracy is computable under a multi-process mesh)."""
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    sharding = NamedSharding(mesh, P())
    if jax.process_count() == 1:
        return tuple(jax.device_put(a, sharding) for a in arrays)
    return tuple(
        jax.make_array_from_process_local_data(sharding, np.asarray(a), a.shape)
        for a in arrays
    )


def numpy_opt_state(opt: optax.GradientTransformation, params):
    """``opt.init(params)`` built as HOST numpy zeros in the exact pytree
    optax would return (``eval_shape`` traces without compiling).

    Running the real init costs a cascade of tiny jit compiles that can
    rival a short worker's whole training run on a small host.  VALID ONLY
    for transforms whose init is all-zeros — true for
    :func:`default_optimizer` (clip_by_global_norm = EmptyState, adam/adamw
    = zeroed moments + count) and asserted by
    tests/test_workloads.py so the two cannot drift apart silently.  A
    transform that stores non-zero values in its state (e.g.
    inject_hyperparams) must use ``opt.init`` directly."""
    import numpy as np

    return jax.tree_util.tree_map(
        lambda s: np.zeros(s.shape, s.dtype), jax.eval_shape(opt.init, params))


def default_optimizer(lr: float, *, clip: Optional[float] = 1.0,
                      weight_decay: float = 0.0) -> optax.GradientTransformation:
    chain = []
    if clip:
        chain.append(optax.clip_by_global_norm(clip))
    if weight_decay:
        chain.append(optax.adamw(lr, weight_decay=weight_decay))
    else:
        chain.append(optax.adam(lr))
    return optax.chain(*chain)
