"""Generic jitted train-step factory shared by the workload entrypoints."""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

import jax
import optax


def make_train_step(
    loss_fn: Callable[[Any, Any], jax.Array],
    optimizer: optax.GradientTransformation,
) -> Callable:
    """Returns jitted ``step(params, opt_state, batch) -> (params, opt_state,
    loss)`` with donated carries so buffers update in place on TPU."""

    def step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return jax.jit(step, donate_argnums=(0, 1))


def train_scan(
    loss_fn: Callable[[Any, Any], jax.Array],
    optimizer: optax.GradientTransformation,
    params: Any,
    opt_state: Any,
    batches: Any,
) -> Tuple[Any, Any, jax.Array]:
    """Run the whole training loop as ONE jitted ``lax.scan`` over stacked
    batches — a single dispatch instead of one per step, which matters
    enormously for small models where per-step Python/dispatch overhead
    rivals the math.  Returns (params, opt_state, last_loss)."""

    def body(carry, batch):
        p, s = carry
        loss, grads = jax.value_and_grad(loss_fn)(p, batch)
        updates, s = optimizer.update(grads, s, p)
        p = optax.apply_updates(p, updates)
        return (p, s), loss

    @jax.jit
    def run(p, s, batches):
        (p, s), losses = jax.lax.scan(body, (p, s), batches)
        return p, s, losses[-1]

    return run(params, opt_state, batches)


def batch_stack(x: jax.Array, y: jax.Array, steps: int, batch_size: int):
    """[n,...] data -> ([steps, bs, ...], [steps, bs]) cycling over n."""
    import jax.numpy as jnp

    n = x.shape[0]
    idx = (jnp.arange(steps)[:, None] * batch_size + jnp.arange(batch_size)[None, :]) % n
    return x[idx], y[idx]


def default_optimizer(lr: float, *, clip: Optional[float] = 1.0,
                      weight_decay: float = 0.0) -> optax.GradientTransformation:
    chain = []
    if clip:
        chain.append(optax.clip_by_global_norm(clip))
    if weight_decay:
        chain.append(optax.adamw(lr, weight_decay=weight_decay))
    else:
        chain.append(optax.adam(lr))
    return optax.chain(*chain)
