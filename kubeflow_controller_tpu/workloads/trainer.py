"""Generic jitted train-step factory shared by the workload entrypoints."""

from __future__ import annotations

import time
from typing import Any, Callable, Optional, Tuple

import jax
import optax


def record_step_telemetry(steps: int, duration_s: float,
                          examples_per_step: int = 0,
                          registry=None) -> None:
    """Publish a training run's step-time/throughput on the obs registry.

    The scan-based trainers execute the whole run as ONE compiled program,
    so per-step timing does not exist host-side; what is recorded is the
    run's mean step time (one histogram observation per run) plus
    cumulative step/example counters and an examples-per-second gauge —
    the numbers future perf PRs cite from ``GET /metrics``."""
    from ..obs.metrics import REGISTRY

    reg = registry or REGISTRY
    if steps <= 0 or duration_s < 0:
        return
    reg.histogram(
        "kctpu_trainer_step_duration_seconds",
        "Mean per-step train time of a completed run (one observation per run)",
    ).observe(duration_s / steps)
    reg.histogram(
        "kctpu_trainer_fit_duration_seconds",
        "Whole-run compiled-train-program wall time",
    ).observe(duration_s)
    reg.counter("kctpu_trainer_steps_total",
                "Training steps completed").inc(steps)
    if examples_per_step > 0:
        reg.counter("kctpu_trainer_examples_total",
                    "Training examples consumed").inc(steps * examples_per_step)
        if duration_s > 0:
            reg.gauge("kctpu_trainer_examples_per_second",
                      "Throughput of the most recent completed run").set(
                steps * examples_per_step / duration_s)


def make_train_step(
    loss_fn: Callable[[Any, Any], jax.Array],
    optimizer: optax.GradientTransformation,
) -> Callable:
    """Returns jitted ``step(params, opt_state, batch) -> (params, opt_state,
    loss)`` with donated carries so buffers update in place on TPU."""

    def step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return jax.jit(step, donate_argnums=(0, 1))


def train_scan_stateful(
    loss_fn: Callable[[Any, Any, Any], Tuple[jax.Array, Any]],
    optimizer: optax.GradientTransformation,
    params: Any,
    opt_state: Any,
    state: Any,
    batches: Any,
) -> Tuple[Any, Any, Any, jax.Array]:
    """Whole training loop as ONE jitted ``lax.scan`` over stacked batches —
    a single dispatch instead of one per step, which matters enormously for
    small models where per-step Python/dispatch overhead rivals the math.

    ``loss_fn(params, batch, state) -> (loss, new_state)`` threads mutable
    model state (e.g. BatchNorm statistics) through the scan.
    Returns (params, state, opt_state, last_loss)."""

    def body(carry, batch):
        p, st, s = carry
        (loss, st), grads = jax.value_and_grad(loss_fn, has_aux=True)(p, batch, st)
        updates, s = optimizer.update(grads, s, p)
        p = optax.apply_updates(p, updates)
        return (p, st, s), loss

    @jax.jit
    def run(p, st, s, batches):
        (p, st, s), losses = jax.lax.scan(body, (p, st, s), batches)
        return p, st, s, losses[-1]

    return run(params, state, opt_state, batches)


def train_scan(
    loss_fn: Callable[[Any, Any], jax.Array],
    optimizer: optax.GradientTransformation,
    params: Any,
    opt_state: Any,
    batches: Any,
) -> Tuple[Any, Any, jax.Array]:
    """Stateless variant of :func:`train_scan_stateful`.
    Returns (params, opt_state, last_loss)."""
    params, _, opt_state, loss = train_scan_stateful(
        lambda p, b, st: (loss_fn(p, b), st),
        optimizer, params, opt_state, None, batches,
    )
    return params, opt_state, loss


def train_scan_dist(
    loss_fn: Callable[[Any, Any], jax.Array],
    optimizer: optax.GradientTransformation,
    params: Any,
    opt_state: Any,
    steps: int,
    mesh,
    axis: str,
    local_batches_fn: Callable[[jax.Array], Any],
    eval_counts_fn: Optional[Callable[[Any, jax.Array], Tuple[jax.Array, jax.Array]]] = None,
    aot_cache: Optional[str] = None,
    examples_per_step: int = 0,
):
    """Distributed data-parallel training as ONE compiled program with ONE
    collective per step.

    The reference's PS data plane ships every gradient tensor to the PS
    each step (ref: examples/workdir/mnist_replica.py:251-264 — one grpc
    round-trip per variable).  The naive SPMD re-expression inherits that
    shape: XLA inserts one all-reduce per gradient leaf, and on a
    process-per-worker gang each collective costs fixed rendezvous latency
    regardless of payload size (measured: ~3.7ms/call for 8 floats or 160k
    floats alike — docs/PERF.md).  So the whole step's cross-worker traffic
    is flattened into a single psum: gradients ravel into one flat buffer,
    the scalar loss rides in the same buffer, and eval reduces through one
    more psum at the end.  Latency-bound collectives make "how many", not
    "how big", the cost model.

    Everything else lives inside the same jit under ``shard_map``:

    - ``local_batches_fn(shard_index) -> batches`` builds this shard's
      slice of every global batch ON DEVICE (leading dims
      ``[steps_per_epoch, local_bs, ...]``); the scan cycles over the epoch
      axis, so a "dataset" is revisited exactly like a host-staged one but
      costs no host generation, no host->device copy, and no global-array
      assembly consensus.
    - ``eval_counts_fn(params, shard_index) -> (num, den)`` returns this
      shard's contribution to a global ratio metric (e.g. correct count,
      example count); the psum'd ratio comes back as the final output.

    ``aot_cache`` (a file path) opts into ahead-of-time executable reuse:
    on miss the compiled executable is serialized there
    (``jax.experimental.serialize_executable``), on hit it is loaded and
    run directly — skipping trace/lower/compile entirely.  On a one-core
    host every process's Python jit pipeline serializes with every other
    process's, and a peer stuck compiling makes its partners burn the core
    spinning in the collective rendezvous, so skipping the pipeline is
    worth more than a warm HLO cache (measured: ~4.4s of per-call overhead
    -> ~0.35s, docs/PERF.md).  The path must be per-process and
    per-program-config (callers embed process index and shape-affecting
    args); a stale or unreadable file falls back to the compile path.

    Returns ``(params, opt_state, last_loss[, metric])``.
    """
    import jax.numpy as jnp
    from jax.flatten_util import ravel_pytree
    from jax.sharding import PartitionSpec as P

    dp = mesh.shape[axis]

    def inner(params, opt_state):
        i = jax.lax.axis_index(axis)
        batches = local_batches_fn(i)
        spe = jax.tree_util.tree_leaves(batches)[0].shape[0]

        def body(carry, t):
            p, s = carry
            b = jax.tree_util.tree_map(
                lambda a: jax.lax.dynamic_index_in_dim(
                    a, t % spe, axis=0, keepdims=False),
                batches)
            # Differentiate w.r.t. a VARYING view of the params: grads of
            # replicated params would get an automatic per-tensor psum
            # inserted in the transpose (one hidden collective per gradient
            # leaf — the exact per-variable shape this function exists to
            # avoid); pvary keeps the local grads local so the one explicit
            # flat psum below is the step's only collective.
            pv = jax.tree_util.tree_map(
                lambda a: jax.lax.pcast(a, axis, to="varying"), p)
            loss, grads = jax.value_and_grad(loss_fn)(pv, b)
            flat, unravel = ravel_pytree(grads)
            # One latency-bound collective for the whole step: grads + loss.
            flat = jax.lax.psum(
                jnp.concatenate([flat, loss[None].astype(flat.dtype)]), axis) / dp
            updates, s = optimizer.update(unravel(flat[:-1]), s, p)
            p = optax.apply_updates(p, updates)
            return (p, s), flat[-1]

        (params, opt_state), losses = jax.lax.scan(
            body, (params, opt_state), jnp.arange(steps, dtype=jnp.int32))
        out = (params, opt_state, losses[-1])
        if eval_counts_fn is not None:
            num, den = eval_counts_fn(params, i)
            nd = jax.lax.psum(
                jnp.stack([jnp.asarray(num, jnp.float32),
                           jnp.asarray(den, jnp.float32)]), axis)
            out = out + (nd[0] / nd[1],)
        return out

    fit = jax.jit(
        jax.shard_map(inner, mesh=mesh, in_specs=(P(), P()), out_specs=P()),
        donate_argnums=(0, 1),
    )

    def _timed(run: Callable[[], Any], cache: str) -> Any:
        # One span + one telemetry record for the whole compiled run (the
        # scan is one dispatch; block_until_ready so the measured time is
        # execution, not dispatch — callers consume the outputs right away).
        from ..obs.trace import span as obs_span
        from .progress import reporter

        # Heartbeats for the opaque compiled-run window: the scan is one
        # dispatch, so a keepalive thread re-publishes liveness until the
        # program returns — then the final beat carries the real step
        # count, throughput, and loss.
        rep = reporter()
        rep.beat(phase="fit")
        rep.start_keepalive()
        try:
            with obs_span("trainer/fit", steps=steps,
                          aot_cache=cache) as sp:
                out = jax.block_until_ready(run())
                sp.args["process"] = jax.process_index()
        finally:
            rep.stop_keepalive()
        dur = sp.dur if sp.dur else 0.0
        record_step_telemetry(steps, dur, examples_per_step)
        try:
            final_loss = float(out[2])
        except (TypeError, IndexError, ValueError):
            final_loss = None
        rep.beat(step=steps, loss=final_loss, phase="fit",
                 examples_per_sec=(steps * examples_per_step / dur
                                   if dur > 0 and examples_per_step else None))
        return out

    if aot_cache:
        import os
        import pickle

        from jax.experimental.serialize_executable import (
            deserialize_and_load,
            serialize,
        )

        if os.path.exists(aot_cache):
            try:
                with open(aot_cache, "rb") as fh:
                    payload, in_tree, out_tree = pickle.load(fh)
                loaded = deserialize_and_load(payload, in_tree, out_tree)
                return _timed(lambda: loaded(params, opt_state), "hit")
            except Exception:
                pass  # stale/corrupt entry: recompile below
        compiled = fit.trace(params, opt_state).lower().compile()
        try:
            tmp = f"{aot_cache}.tmp.{os.getpid()}"
            with open(tmp, "wb") as fh:
                pickle.dump(serialize(compiled), fh)
            os.replace(tmp, aot_cache)
        except Exception:
            pass  # cache write is best-effort
        return _timed(lambda: compiled(params, opt_state), "miss")
    return _timed(lambda: fit(params, opt_state), "off")


def batch_stack(x: jax.Array, y: jax.Array, steps: int, batch_size: int):
    """[n,...] data -> ([steps, bs, ...], [steps, bs]) cycling over n."""
    import jax.numpy as jnp

    n = x.shape[0]
    idx = (jnp.arange(steps)[:, None] * batch_size + jnp.arange(batch_size)[None, :]) % n
    return x[idx], y[idx]


def global_batches(mesh, axis: str, arrays, global_batch: int):
    """Host-local stacked batches -> ONE global array per input, steps
    unsharded and the batch dim sharded over ``axis``.

    Single-process: a plain device_put.  Multi-process (classic Worker gangs
    and multi-host TPU slices): each process contributes its
    ``global_batch / process_count`` rows of every step's batch
    (``jax.make_array_from_process_local_data``), so the scan trains one
    shared model over the union of the workers' shards — the all-reduce
    re-expression of the reference's PS data plane, not N private runs.
    """
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    sharding = NamedSharding(mesh, P(None, axis))
    if jax.process_count() == 1:
        return tuple(jax.device_put(a, sharding) for a in arrays)
    out = []
    for a in arrays:
        gshape = (a.shape[0], global_batch) + tuple(a.shape[2:])
        out.append(jax.make_array_from_process_local_data(
            sharding, np.asarray(a), gshape))
    return tuple(out)


def replicate_global(mesh, *arrays):
    """Fully-replicated global arrays (every process passes identical data;
    used for eval sets so accuracy is computable under a multi-process mesh)."""
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    sharding = NamedSharding(mesh, P())
    if jax.process_count() == 1:
        return tuple(jax.device_put(a, sharding) for a in arrays)
    return tuple(
        jax.make_array_from_process_local_data(sharding, np.asarray(a), a.shape)
        for a in arrays
    )


def numpy_opt_state(opt: optax.GradientTransformation, params):
    """``opt.init(params)`` built as HOST numpy zeros in the exact pytree
    optax would return (``eval_shape`` traces without compiling).

    Running the real init costs a cascade of tiny jit compiles that can
    rival a short worker's whole training run on a small host.  VALID ONLY
    for transforms whose init is all-zeros — true for
    :func:`default_optimizer` (clip_by_global_norm = EmptyState, adam/adamw
    = zeroed moments + count) and asserted by
    tests/test_workloads.py so the two cannot drift apart silently.  A
    transform that stores non-zero values in its state (e.g.
    inject_hyperparams) must use ``opt.init`` directly."""
    import numpy as np

    return jax.tree_util.tree_map(
        lambda s: np.zeros(s.shape, s.dtype), jax.eval_shape(opt.init, params))


def default_optimizer(lr: float, *, clip: Optional[float] = 1.0,
                      weight_decay: float = 0.0) -> optax.GradientTransformation:
    chain = []
    if clip:
        chain.append(optax.clip_by_global_norm(clip))
    if weight_decay:
        chain.append(optax.adamw(lr, weight_decay=weight_decay))
    else:
        chain.append(optax.adam(lr))
    return optax.chain(*chain)
