"""Generic jitted train-step factory shared by the workload entrypoints."""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

import jax
import optax


def make_train_step(
    loss_fn: Callable[[Any, Any], jax.Array],
    optimizer: optax.GradientTransformation,
) -> Callable:
    """Returns jitted ``step(params, opt_state, batch) -> (params, opt_state,
    loss)`` with donated carries so buffers update in place on TPU."""

    def step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return jax.jit(step, donate_argnums=(0, 1))


def train_scan_stateful(
    loss_fn: Callable[[Any, Any, Any], Tuple[jax.Array, Any]],
    optimizer: optax.GradientTransformation,
    params: Any,
    opt_state: Any,
    state: Any,
    batches: Any,
) -> Tuple[Any, Any, Any, jax.Array]:
    """Whole training loop as ONE jitted ``lax.scan`` over stacked batches —
    a single dispatch instead of one per step, which matters enormously for
    small models where per-step Python/dispatch overhead rivals the math.

    ``loss_fn(params, batch, state) -> (loss, new_state)`` threads mutable
    model state (e.g. BatchNorm statistics) through the scan.
    Returns (params, state, opt_state, last_loss)."""

    def body(carry, batch):
        p, st, s = carry
        (loss, st), grads = jax.value_and_grad(loss_fn, has_aux=True)(p, batch, st)
        updates, s = optimizer.update(grads, s, p)
        p = optax.apply_updates(p, updates)
        return (p, st, s), loss

    @jax.jit
    def run(p, st, s, batches):
        (p, st, s), losses = jax.lax.scan(body, (p, st, s), batches)
        return p, st, s, losses[-1]

    return run(params, state, opt_state, batches)


def train_scan(
    loss_fn: Callable[[Any, Any], jax.Array],
    optimizer: optax.GradientTransformation,
    params: Any,
    opt_state: Any,
    batches: Any,
) -> Tuple[Any, Any, jax.Array]:
    """Stateless variant of :func:`train_scan_stateful`.
    Returns (params, opt_state, last_loss)."""
    params, _, opt_state, loss = train_scan_stateful(
        lambda p, b, st: (loss_fn(p, b), st),
        optimizer, params, opt_state, None, batches,
    )
    return params, opt_state, loss


def batch_stack(x: jax.Array, y: jax.Array, steps: int, batch_size: int):
    """[n,...] data -> ([steps, bs, ...], [steps, bs]) cycling over n."""
    import jax.numpy as jnp

    n = x.shape[0]
    idx = (jnp.arange(steps)[:, None] * batch_size + jnp.arange(batch_size)[None, :]) % n
    return x[idx], y[idx]


def global_batches(mesh, axis: str, arrays, global_batch: int):
    """Host-local stacked batches -> ONE global array per input, steps
    unsharded and the batch dim sharded over ``axis``.

    Single-process: a plain device_put.  Multi-process (classic Worker gangs
    and multi-host TPU slices): each process contributes its
    ``global_batch / process_count`` rows of every step's batch
    (``jax.make_array_from_process_local_data``), so the scan trains one
    shared model over the union of the workers' shards — the all-reduce
    re-expression of the reference's PS data plane, not N private runs.
    """
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    sharding = NamedSharding(mesh, P(None, axis))
    if jax.process_count() == 1:
        return tuple(jax.device_put(a, sharding) for a in arrays)
    out = []
    for a in arrays:
        gshape = (a.shape[0], global_batch) + tuple(a.shape[2:])
        out.append(jax.make_array_from_process_local_data(
            sharding, np.asarray(a), gshape))
    return tuple(out)


def replicate_global(mesh, *arrays):
    """Fully-replicated global arrays (every process passes identical data;
    used for eval sets so accuracy is computable under a multi-process mesh)."""
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    sharding = NamedSharding(mesh, P())
    if jax.process_count() == 1:
        return tuple(jax.device_put(a, sharding) for a in arrays)
    return tuple(
        jax.make_array_from_process_local_data(sharding, np.asarray(a), a.shape)
        for a in arrays
    )


def default_optimizer(lr: float, *, clip: Optional[float] = 1.0,
                      weight_decay: float = 0.0) -> optax.GradientTransformation:
    chain = []
    if clip:
        chain.append(optax.clip_by_global_norm(clip))
    if weight_decay:
        chain.append(optax.adamw(lr, weight_decay=weight_decay))
    else:
        chain.append(optax.adam(lr))
    return optax.chain(*chain)
