"""Distributed MNIST — the Worker/TPU replica workload.

The reference wires N workers + M parameter servers over grpc and ships
gradients to the PS every step (ref: examples/workdir/mnist_replica.py:
113-141, 251-264).  TPU-native, the PS tier disappears: parameters are
replicated (or sharded) over the device mesh and gradients all-reduce over
ICI — this script is the data-parallel re-expression of the same training
run (200 steps, batch 100 by default, matching docs/get_started.md:49-63).

Roles:
- launched with the TF-contract args the planner still generates for
  PS/Worker replicas (``--job_name --task_index ...``): a ``ps`` role
  parks forever, the analog of ``server.join()`` (mnist_replica.py:121-122)
  — the data plane it used to host now rides XLA collectives;
  a ``worker`` role trains its shard.
- launched under the TPU replica env contract: joins via jax.distributed
  (runtime.initialize) and trains over the global mesh.
"""

from __future__ import annotations

import argparse
import os
import signal
import sys
import time


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description="distributed MNIST")
    # TF-contract args injected by the planner (planner/materialize.py
    # tf_cluster_args; ref: distributed.go:130-162).
    p.add_argument("--job_name", default="")
    p.add_argument("--task_index", type=int, default=-1)
    p.add_argument("--worker_hosts", default="")
    p.add_argument("--ps_hosts", default="")
    p.add_argument("--steps", type=int, default=200)
    p.add_argument("--batch-size", type=int, default=100, help="global batch")
    p.add_argument("--lr", type=float, default=5e-3)
    p.add_argument("--train-size", type=int, default=8192)
    p.add_argument("--eval-size", type=int, default=2048)
    p.add_argument("--target-accuracy", type=float, default=0.0)
    p.add_argument("--platform", default=os.environ.get("WORKLOAD_PLATFORM", ""))
    args = p.parse_args(argv)

    if args.job_name == "ps":
        # PS data plane replaced by XLA collectives; park until the gang is
        # torn down, like server.join() (the updater ignores PS state for
        # job success — ref: pkg/controller/updater/distributed.go:47-59).
        # sigwait only catches signals that are blocked; unblocked, SIGTERM
        # would run its default disposition and exit 143 instead of 0.
        park = {signal.SIGTERM, signal.SIGINT}
        signal.pthread_sigmask(signal.SIG_BLOCK, park)
        signal.sigwait(park)
        return 0

    if args.platform:
        os.environ["JAX_PLATFORMS"] = args.platform
    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..models import mnist as m
    from ..parallel import AXIS_DATA, MeshSpec, build_mesh
    from . import data as d
    from .runtime import JobRuntime
    from .trainer import batch_stack, default_optimizer, train_scan

    rt = JobRuntime.from_env()
    rt.initialize()

    # Worker replicas each train their static shard of the global batch and
    # run their own mesh over local devices; TPU replicas form one global
    # mesh across processes.
    workers = max(1, len(args.worker_hosts.split(",")) if args.worker_hosts else rt.num_processes)
    worker_id = args.task_index if args.task_index >= 0 else rt.process_id

    mesh = build_mesh(MeshSpec(dp=-1, fsdp=1))

    x, y = d.synthetic_mnist(jax.random.PRNGKey(1), args.train_size)
    ex, ey = d.synthetic_mnist(jax.random.PRNGKey(2), args.eval_size)
    if args.task_index >= 0 and workers > 1:
        # Classic worker pods are separate training processes (async-PS
        # analog): each owns a static shard of the data.
        x = d.shard_for_process(x, worker_id, workers)
        y = d.shard_for_process(y, worker_id, workers)

    params = m.mlp_init(jax.random.PRNGKey(0))
    opt = default_optimizer(args.lr)
    opt_state = opt.init(params)

    # Round the global batch down to a multiple of the data-parallel size
    # (the reference's batch 100 over e.g. 8 devices -> 96 per step).
    dp = mesh.shape[AXIS_DATA]
    bs = max(dp, args.batch_size - args.batch_size % dp)
    start = time.time()
    with jax.set_mesh(mesh):
        xb, yb = batch_stack(x, y, args.steps, bs)
        step_sharding = NamedSharding(mesh, P(None, AXIS_DATA))
        batches = (
            jax.device_put(xb, step_sharding),
            jax.device_put(yb, step_sharding),
        )
        params, opt_state, loss = train_scan(
            lambda p, b: m.mlp_loss(p, b[0], b[1]), opt, params, opt_state, batches
        )
        loss = float(loss)
    elapsed = time.time() - start

    acc = float(m.mlp_accuracy(params, ex, ey))
    print(f"Worker {worker_id}/{workers} on {jax.device_count()} devices "
          f"(mesh dp={mesh.shape[AXIS_DATA]})")
    print(f"Training elapsed time: {elapsed:f} s")
    print(f"Final loss: {loss:f}; eval accuracy: {acc:f}")
    if rt.model_dir and (args.task_index <= 0 or rt.is_chief):
        from .checkpoint import CheckpointManager

        CheckpointManager(rt.model_dir).save(args.steps, params, opt_state)
        print(f"Checkpoint saved to {rt.model_dir}")
    if args.target_accuracy and acc < args.target_accuracy:
        print(f"accuracy {acc} below target {args.target_accuracy}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
