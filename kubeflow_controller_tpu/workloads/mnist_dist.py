"""Distributed MNIST — the Worker/TPU replica workload.

The reference wires N workers + M parameter servers over grpc and ships
gradients to the PS every step (ref: examples/workdir/mnist_replica.py:
113-141, 251-264).  TPU-native, the PS tier disappears: the worker pods
form ONE jax.distributed cluster (coordinator env injected by the planner,
or derived from ``--worker_hosts`` exactly as the reference workload feeds
tf.train.ClusterSpec), parameters are replicated over the global mesh, and
gradients all-reduce over XLA collectives — one shared model, the same
semantics as the reference's PS training with the grpc data plane replaced
by ICI/gloo (200 steps, batch 100 by default, matching
docs/get_started.md:49-63).

Roles:
- ``ps``: parks forever, the analog of ``server.join()``
  (mnist_replica.py:121-122) — the data plane it used to host now rides
  XLA collectives.
- ``worker`` / TPU replica: joins via jax.distributed (runtime.initialize),
  feeds its shard of every global batch, trains over the global mesh.
"""

from __future__ import annotations

import argparse
import os
import signal
import sys
import time


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description="distributed MNIST")
    # TF-contract args injected by the planner (planner/materialize.py
    # tf_cluster_args; ref: distributed.go:130-162).
    p.add_argument("--job_name", default="")
    p.add_argument("--task_index", type=int, default=-1)
    p.add_argument("--worker_hosts", default="")
    p.add_argument("--ps_hosts", default="")
    p.add_argument("--steps", type=int, default=200)
    p.add_argument("--batch-size", type=int, default=100, help="global batch")
    p.add_argument("--lr", type=float, default=5e-3)
    p.add_argument("--train-size", type=int, default=8192)
    p.add_argument("--eval-size", type=int, default=2048)
    p.add_argument("--target-accuracy", type=float, default=0.0)
    p.add_argument("--platform", default=os.environ.get("WORKLOAD_PLATFORM", ""))
    args = p.parse_args(argv)

    if args.job_name == "ps":
        # PS data plane replaced by XLA collectives; park until the gang is
        # torn down, like server.join() (the updater ignores PS state for
        # job success — ref: pkg/controller/updater/distributed.go:47-59).
        # sigwait only catches signals that are blocked; unblocked, SIGTERM
        # would run its default disposition and exit 143 instead of 0.
        park = {signal.SIGTERM, signal.SIGINT}
        signal.pthread_sigmask(signal.SIG_BLOCK, park)
        signal.sigwait(park)
        return 0

    if args.platform:
        os.environ["JAX_PLATFORMS"] = args.platform
    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    from ..models import mnist as m
    from ..parallel import AXIS_DATA, MeshSpec, build_mesh
    from . import data as d
    from .runtime import JobRuntime
    from .trainer import (
        batch_stack,
        default_optimizer,
        global_batches,
        replicate_global,
        train_scan,
    )

    t_start = time.time()
    rt = JobRuntime.from_env()
    rt.merge_tf_args(args.job_name, args.task_index, args.worker_hosts)
    rt.initialize()
    t_rendezvous = time.time()

    # One global mesh over every process's devices: classic Worker gangs and
    # TPU slices land on the same code path.
    pc, proc = jax.process_count(), jax.process_index()
    mesh = build_mesh(MeshSpec(dp=-1, fsdp=1))

    x, y = d.synthetic_mnist(jax.random.PRNGKey(1), args.train_size)
    ex, ey = d.synthetic_mnist(jax.random.PRNGKey(2), args.eval_size)
    t_data = time.time()
    if pc > 1:
        # Each process owns a static shard of the data and feeds its share
        # of every global batch.
        x = d.shard_for_process(x, proc, pc)
        y = d.shard_for_process(y, proc, pc)

    params = m.mlp_init(jax.random.PRNGKey(0))  # same seed -> same init everywhere
    opt = default_optimizer(args.lr)
    opt_state = opt.init(params)

    # Round the global batch down to a multiple of the data-parallel size
    # (the reference's batch 100 over e.g. 8 devices -> 96 per step).
    dp = mesh.shape[AXIS_DATA]
    bs = max(dp, args.batch_size - args.batch_size % dp)
    start = time.time()
    with jax.set_mesh(mesh):
        xb, yb = batch_stack(x, y, args.steps, bs // pc)
        batches = global_batches(mesh, AXIS_DATA, (xb, yb), bs)
        t_batches = time.time()
        params, opt_state, loss = train_scan(
            lambda p, b: m.mlp_loss(p, b[0], b[1]), opt, params, opt_state, batches
        )
        loss = float(loss)
        elapsed = time.time() - start
        t_train_done = time.time()
    # Eval OUTSIDE the mesh: params are fully replicated, so each process
    # holds them locally and the identical eval set needs no
    # replicate_global consensus or in-mesh collectives at all.
    host_params = jax.device_get(params)
    acc = float(jax.jit(m.mlp_accuracy)(host_params, ex, ey))
    t_eval = time.time()

    print(f"Worker {proc}/{pc} on {jax.device_count()} devices "
          f"(mesh dp={dp})")
    # Phase breakdown for the headline-bench profile (bench.py parses it):
    # rendezvous = jax.distributed join, data = synthetic gen, batches =
    # stack + global-array assembly (a cross-process consensus point),
    # train = the scan (incl. compile-or-cache-load), eval = accuracy.
    print(f"Phase times: rendezvous={t_rendezvous - t_start:.3f}s "
          f"data={t_data - t_rendezvous:.3f}s "
          f"batches={t_batches - start:.3f}s "
          f"train={t_train_done - t_batches:.3f}s "
          f"eval={t_eval - t_train_done:.3f}s "
          f"total={time.time() - t_start:.3f}s")
    print(f"Training elapsed time: {elapsed:f} s")
    print(f"Final loss: {loss:f}; eval accuracy: {acc:f}")
    if rt.model_dir:
        from .checkpoint import CheckpointManager

        # Collective under a multi-process mesh: every process participates.
        CheckpointManager(rt.model_dir).save(args.steps, params, opt_state)
        if proc == 0:
            print(f"Checkpoint saved to {rt.model_dir}")
    if args.target_accuracy and acc < args.target_accuracy:
        print(f"accuracy {acc} below target {args.target_accuracy}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
