"""Distributed MNIST — the Worker/TPU replica workload.

The reference wires N workers + M parameter servers over grpc and ships
gradients to the PS every step (ref: examples/workdir/mnist_replica.py:
113-141, 251-264).  TPU-native, the PS tier disappears: the worker pods
form ONE jax.distributed cluster (coordinator env injected by the planner,
or derived from ``--worker_hosts`` exactly as the reference workload feeds
tf.train.ClusterSpec), parameters are replicated over the global mesh, and
gradients all-reduce over XLA collectives — one shared model, the same
semantics as the reference's PS training with the grpc data plane replaced
by ICI/gloo (200 steps, batch 100 by default, matching
docs/get_started.md:49-63).

Roles:
- ``ps``: parks forever, the analog of ``server.join()``
  (mnist_replica.py:121-122) — the data plane it used to host now rides
  XLA collectives.
- ``worker`` / TPU replica: joins via jax.distributed (runtime.initialize),
  trains over the global mesh.

Two fit shapes:

- **scan** (default): the whole workload is ONE compiled program per
  worker (train_scan_dist) — batch generation, the training scan with a
  single fused flat-gradient all-reduce per step, and the sharded eval.
  Minimum dispatch overhead; progress is keepalive-only while the program
  runs opaque.
- **step-loop** (``--step-loop``): the time-to-first-step pipeline.  One
  AOT-compiled step executable (trainer.make_dist_step) driven per-step:
  host setup (dataset synthesis, param init — pure numpy) runs on a
  background thread OVERLAPPED with the rendezvous, the step executable is
  AOT-compiled from abstract shapes (post-rendezvous, concurrently with
  that setup — compile needs shapes, not values; cache-hit via
  compile_cache skips it entirely), and the first step beats ``step=1``
  the moment it completes.  ``--no-overlap`` is the serial baseline
  (rendezvous, then setup, then compile — the pre-pipeline ordering)
  measured by ``bench.py --ttfs``.
"""

from __future__ import annotations

import argparse
import os
import signal
import sys
import time


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description="distributed MNIST")
    # TF-contract args injected by the planner (planner/materialize.py
    # tf_cluster_args; ref: distributed.go:130-162).
    p.add_argument("--job_name", default="")
    p.add_argument("--task_index", type=int, default=-1)
    p.add_argument("--worker_hosts", default="")
    p.add_argument("--ps_hosts", default="")
    p.add_argument("--steps", type=int, default=200)
    p.add_argument("--batch-size", type=int, default=100, help="global batch")
    p.add_argument("--lr", type=float, default=5e-3)
    p.add_argument("--train-size", type=int, default=8192)
    p.add_argument("--eval-size", type=int, default=2048)
    p.add_argument("--target-accuracy", type=float, default=0.0)
    p.add_argument("--platform", default=os.environ.get("WORKLOAD_PLATFORM", ""))
    p.add_argument("--aot-cache", default=os.environ.get("WORKLOAD_AOT_CACHE", ""),
                   help="directory for serialized-executable reuse across "
                        "identical jobs (see trainer.train_scan_dist); "
                        "defaults to $KCTPU_COMPILE_CACHE when that is set")
    p.add_argument("--step-loop", action="store_true",
                   default=bool(os.environ.get("WORKLOAD_STEP_LOOP")),
                   help="per-step-dispatch TTFS pipeline instead of the "
                        "single-program scan (real per-step progress beats, "
                        "AOT step executable, overlapped host setup)")
    p.add_argument("--checkpoint-every", type=int,
                   default=int(os.environ.get("KCTPU_CHECKPOINT_EVERY", "0")
                               or "0"),
                   help="step-loop mode: async CheckpointManager.save every "
                        "N steps into MODEL_DIR (0 = only the final save); "
                        "bounds the steps a mid-fit kill can lose — "
                        "injected from spec.checkpoint_every_steps")
    p.add_argument("--step-sleep", type=float,
                   default=float(os.environ.get("KCTPU_STEP_SLEEP", "0")
                                 or "0"),
                   help="step-loop mode: host-side sleep per step (seconds) "
                        "— stretches the fit window so chaos/fault benches "
                        "can kill reliably mid-fit")
    p.add_argument("--no-overlap", action="store_true",
                   default=bool(os.environ.get("KCTPU_NO_OVERLAP")),
                   help="serial baseline: run host setup after rendezvous "
                        "instead of overlapping the two (bench.py --ttfs)")
    args = p.parse_args(argv)

    if args.job_name == "ps":
        # PS data plane replaced by XLA collectives; park until the gang is
        # torn down, like server.join() (the updater ignores PS state for
        # job success — ref: pkg/controller/updater/distributed.go:47-59).
        # sigwait only catches signals that are blocked; unblocked, SIGTERM
        # would run its default disposition and exit 143 instead of 0.
        park = {signal.SIGTERM, signal.SIGINT}
        signal.pthread_sigmask(signal.SIG_BLOCK, park)
        signal.sigwait(park)
        return 0

    if args.platform:
        os.environ["JAX_PLATFORMS"] = args.platform
    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    import jax.numpy as jnp

    from ..models import mnist as m
    from ..obs import trace as obs_trace
    from ..parallel import AXIS_DATA, MeshSpec, build_mesh
    from . import data as d
    from .compile_cache import enable_persistent_cache
    from .runtime import HostSetup, JobRuntime
    from .trainer import default_optimizer, numpy_opt_state

    # Launch-path phases as obs spans (the single source of truth for the
    # phase breakdown: the "Phase times:" line below and bench.py's
    # --trace-out / --ttfs dumps all come from these).
    t_start = time.time()
    # Persistent compile cache BEFORE anything can compile: both the XLA
    # disk cache and the serialized-executable layer root here.
    cache_dir = enable_persistent_cache()
    aot_dir = args.aot_cache or cache_dir

    rt = JobRuntime.from_env()
    rt.merge_tf_args(args.job_name, args.task_index, args.worker_hosts)

    # Recovery plane (opt-in via $KCTPU_GANG_MONITOR): the gang guard's
    # heartbeat files + peer monitor turn "survivor hangs in a torn
    # collective" into "survivor exits for re-rendezvous" — started before
    # the rendezvous so a peer that dies INSIDE the join is detected too.
    from ..recovery.rendezvous import guard_from_env

    guard = guard_from_env(rt)
    if guard is not None:
        guard.start()

    # Host setup — pure numpy, so it can run CONCURRENTLY with the
    # rendezvous (and, in step-loop mode, with the AOT compile: setup
    # produces values, compile needs only shapes).  The serial baseline
    # (--no-overlap) runs exactly this work inline after rendezvous.
    def host_setup():
        means = d.mnist_teacher_means()
        params = m.mlp_init(0)  # same seed -> same init everywhere
        opt_state = numpy_opt_state(default_optimizer(args.lr), params)
        train = eval_set = None
        if args.step_loop:
            train = d.synthetic_mnist_np(1, args.train_size)
            eval_set = d.synthetic_mnist_np(2, args.eval_size)
        return means, params, opt_state, train, eval_set

    setup = HostSetup(host_setup, overlap=not args.no_overlap)

    with obs_trace.span("workload/rendezvous",
                        task_index=args.task_index) as sp_rdv:
        rt.initialize()

    # One global mesh over every process's devices: classic Worker gangs and
    # TPU slices land on the same code path.
    pc, proc = jax.process_count(), jax.process_index()
    with obs_trace.span("workload/init", process=proc) as sp_init:
        mesh = build_mesh(MeshSpec(dp=-1, fsdp=1))
        opt = default_optimizer(args.lr)
        # Round the global batch down to a multiple of the data-parallel size
        # (the reference's batch 100 over e.g. 8 devices -> 96 per step).
        dp = mesh.shape[AXIS_DATA]
        bs = max(dp, args.batch_size - args.batch_size % dp)
        local_bs = bs // dp
        spe = max(1, args.train_size // bs)  # steps per epoch
        eval_local = max(1, args.eval_size // dp)

    if args.step_loop:
        fit_out = _fit_step_loop(args, jax, jnp, m, rt, setup, mesh, opt,
                                 dp, pc, proc, bs, spe, aot_dir)
    else:
        fit_out = _fit_scan(args, jax, jnp, d, m, rt, setup, mesh, opt,
                            dp, pc, proc, bs, local_bs, spe, eval_local,
                            aot_dir)
    loss, acc, sp_fit, params, opt_state = fit_out
    elapsed = sp_fit.dur

    print(f"Worker {proc}/{pc} on {jax.device_count()} devices "
          f"(mesh dp={dp})")
    # Phase breakdown (bench.py reads the same spans from the trace dump).
    # rendezvous = jax.distributed join; init = mesh + batch math; the
    # host_setup span runs concurrently under overlap (bench reports it
    # separately); fit covers compile + staging + train + eval.
    print(f"Phase times: rendezvous={sp_rdv.dur:.3f}s "
          f"init={sp_init.dur:.3f}s "
          f"fit={sp_fit.dur:.3f}s "
          f"total={time.time() - t_start:.3f}s")
    print(f"Training elapsed time: {elapsed:f} s")
    print(f"Final loss: {loss:f}; eval accuracy: {acc:f}")
    # Explicit span dump: warm-forked pods exit via os._exit (no atexit).
    obs_trace.dump_to_env_dir()
    if rt.model_dir:
        from .checkpoint import CheckpointManager

        # Collective under a multi-process mesh: every process participates.
        CheckpointManager(rt.model_dir).save(args.steps, params, opt_state)
        if proc == 0:
            print(f"Checkpoint saved to {rt.model_dir}")
    if guard is not None:
        # Clean completion: the done marker BEFORE the exit barrier, so a
        # fast peer's silence is never mistaken for death.
        guard.mark_done()
    if pc > 1:
        # Leave together, then disconnect cleanly: process 0 hosts the
        # coordination service, and an early exit turns a peer still
        # finishing its (local) eval — or even just its interpreter
        # teardown — into a TSL fatal ("Terminating process...") and a
        # pointless OnFailure restart against a dead coordinator.  The
        # barrier ends the device work in lockstep; the explicit shutdown
        # stops the background error-polling before anyone's service goes
        # away (observed as a rare warm-run flake without it).
        try:
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices("mnist-dist-done")
        except Exception:  # noqa: BLE001 - best-effort; exit skew is rare
            pass
        try:
            jax.distributed.shutdown()
        except Exception:  # noqa: BLE001
            pass
    if args.target_accuracy and acc < args.target_accuracy:
        print(f"accuracy {acc} below target {args.target_accuracy}", file=sys.stderr)
        return 1
    return 0


def _fit_scan(args, jax, jnp, d, m, rt, setup, mesh, opt, dp, pc, proc,
              bs, local_bs, spe, eval_local, aot_dir):
    """The single-program scan fit (the headline-bench path)."""
    from ..obs import trace as obs_trace
    from ..parallel import AXIS_DATA
    from .trainer import train_scan_dist

    # Dataset = train_size samples revisited epoch-by-epoch, regenerated
    # identically on every shard in-program (see synthetic_mnist_traced);
    # each shard slices its columns of every batch.  Host numpy templates
    # on purpose: the traced generator closes over them as a compile-time
    # constant.
    means, params, opt_state, _, _ = setup.result()

    def local_batches(i):
        x, y = d.synthetic_mnist_traced(1, spe * bs, means)
        x = x.reshape(spe, bs, m.IMAGE_PIXELS)
        y = y.reshape(spe, bs)
        return (jax.lax.dynamic_slice_in_dim(x, i * local_bs, local_bs, axis=1),
                jax.lax.dynamic_slice_in_dim(y, i * local_bs, local_bs, axis=1))

    def eval_counts(p, i):
        ex, ey = d.synthetic_mnist_traced(2, dp * eval_local, means)
        ex = jax.lax.dynamic_slice_in_dim(ex, i * eval_local, eval_local, axis=0)
        ey = jax.lax.dynamic_slice_in_dim(ey, i * eval_local, eval_local, axis=0)
        correct = jnp.sum(jnp.argmax(m.mlp_apply(p, ex), axis=-1) == ey)
        return correct, jnp.asarray(eval_local, jnp.float32)

    aot = ""
    if aot_dir:
        os.makedirs(aot_dir, exist_ok=True)
        # lr is baked into the compiled program as a constant (the optax
        # chain closes over it), so it MUST be part of the key: two jobs
        # differing only in --lr must not share an executable.
        aot = os.path.join(
            aot_dir,
            f"mnist-dist-s{args.steps}-b{bs}-n{args.train_size}"
            f"-e{args.eval_size}-lr{args.lr:g}-dp{dp}-pc{pc}-p{proc}.aot")

    # The whole job — per-step batch generation, the steps-long scan with
    # its single fused all-reduce, and the sharded eval — is ONE compiled
    # program; `fit` below is one dispatch per worker.
    with obs_trace.span("workload/fit", process=proc, steps=args.steps) as sp_fit:
        params, opt_state, loss, acc = train_scan_dist(
            lambda p, b: m.mlp_loss(p, b[0], b[1]), opt, params, opt_state,
            args.steps, mesh, AXIS_DATA, local_batches, eval_counts,
            aot_cache=aot, examples_per_step=bs,
        )
        loss, acc = float(loss), float(acc)
    return loss, acc, sp_fit, params, opt_state


def _fit_step_loop(args, jax, jnp, m, rt, setup, mesh, opt, dp, pc, proc,
                   bs, spe, aot_dir):
    """The TTFS pipeline fit: AOT step executable + per-step dispatch.

    Ordering is the whole point: the step is compiled (or cache-loaded)
    from ABSTRACT shapes immediately after rendezvous, while the host
    setup thread may still be synthesizing data — then data staging, then
    the first step (the pipeline's finish line), then the rest."""
    import numpy as np

    from ..obs import trace as obs_trace
    from ..parallel import AXIS_DATA
    from .compile_cache import aot_compile, fingerprint
    from .trainer import (
        global_batches,
        make_dist_step,
        replicate_global,
        replicate_pytree,
        train_step_loop_dist,
    )
    from jax.sharding import NamedSharding, PartitionSpec as P

    from .compile_cache import aot_supported

    with obs_trace.span("workload/fit", process=proc, steps=args.steps,
                        step_loop=True) as sp_fit:
        # Donate the carries only where donated executables survive the
        # serialize/deserialize round trip (compile_cache.aot_supported);
        # elsewhere the donation-free form costs a ~ms/step copy and buys
        # the whole serialized-executable warm path.
        donate = aot_supported()
        step = make_dist_step(lambda p, b: m.mlp_loss(p, b[0], b[1]), opt,
                              mesh, AXIS_DATA, donate=donate)
        # Abstract twins of what host_setup is concurrently building: the
        # numpy init's shapes via eval_shape (runs the cheap init math,
        # keeps only shapes) and the optax state tree from opt.init's
        # traced shape — no data required, which is why this compile can
        # run while the dataset is still being synthesized.
        p_abs = jax.eval_shape(lambda: m.mlp_init(0))
        s_abs = jax.eval_shape(opt.init, p_abs)
        batch_sharding = NamedSharding(mesh, P(None, AXIS_DATA))
        x_abs = jax.ShapeDtypeStruct((spe, bs, m.IMAGE_PIXELS), np.float32,
                                     sharding=batch_sharding)
        y_abs = jax.ShapeDtypeStruct((spe, bs), np.int32,
                                     sharding=batch_sharding)
        t_abs = jax.ShapeDtypeStruct((), np.int32)
        key = fingerprint(workload="mnist-dist-step", model="mlp",
                          dtype="float32", lr=args.lr, bs=bs, spe=spe,
                          dp=dp, pc=pc, proc=proc, donate=donate,
                          platform=args.platform or "default")
        if args.no_overlap:
            # Faithful pre-pipeline ordering: rendezvous, THEN host setup,
            # THEN compile, each fully serialized on the critical path.
            means, params, opt_state, train, eval_set = setup.result()
        res = aot_compile(step, (p_abs, s_abs, x_abs, y_abs, t_abs),
                          key=key, cache_dir=aot_dir, donated=donate)

        means, params, opt_state, train, eval_set = setup.result()
        with obs_trace.span("workload/stage", process=proc):
            # Stack the epoch's batches [spe, bs, ...] and contribute this
            # process's columns of every batch (the host-staged analog of
            # the scan mode's in-program generation).
            x_np, y_np = train
            idx = (np.arange(spe)[:, None] * bs + np.arange(bs)[None, :]) \
                % x_np.shape[0]
            rows = bs // pc
            cols = slice(proc * rows, (proc + 1) * rows)
            x_all, y_all = global_batches(
                mesh, AXIS_DATA,
                (x_np[idx][:, cols], y_np[idx][:, cols].astype(np.int32)), bs)
            params = replicate_pytree(mesh, params)
            opt_state = replicate_pytree(mesh, opt_state)

        # Checkpoint-resume (recovery plane): restore the latest readable
        # step BEFORE the first beat — a replacement/restarted replica
        # resumes where the gang's checkpoints left off instead of at step
        # 0, and the progress plane reports resumed_from_step so a
        # backward-jumping step counter reads as a resume, not a stall.
        start_step = 0
        mgr = None
        ck_fn = None
        if rt.model_dir:
            from ..obs import trace as _tr
            from .checkpoint import CheckpointManager
            from .progress import reporter as _reporter

            mgr = CheckpointManager(rt.model_dir)
            # Elastic plane: the width that WROTE these checkpoints comes
            # from the marker, this generation's width from the runtime
            # env ($KCTPU_GANG_WIDTH) — never from any job spec.  A
            # mismatch makes this restore a RE-SHARD: the same model
            # state fans out over a different member count (data shards
            # rebalance by construction — sharding is keyed on the
            # runtime width), and the beats say phase="reshard" so the
            # controller's stall detector holds its frozen-step deadline
            # through the transition.
            prev_width = mgr.read_width()
            phase = ("reshard"
                     if prev_width is not None and prev_width != rt.gang_width
                     else "restore")
            if mgr.latest_step() is not None:
                _reporter().beat(phase=phase)
                with _tr.span("workload/restore", process=proc,
                              reshard=(phase == "reshard")) as sp_r:
                    params, opt_state, start_step = mgr.restore(
                        params, opt_state)
                    sp_r.args["step"] = start_step
                start_step = min(start_step, args.steps)
                _reporter().beat(step=start_step, phase=phase,
                                 resumed_from_step=start_step)
            if proc == 0:
                mgr.write_width(rt.gang_width)
            if args.checkpoint_every > 0:
                def ck_fn(s, p, o, _mgr=mgr):
                    _mgr.save(s, p, o, wait=False)

        step_fn = res.compiled
        if args.step_sleep > 0:
            def step_fn(p, s, x, y, t, _inner=res.compiled,
                        _zz=args.step_sleep):
                time.sleep(_zz)
                return _inner(p, s, x, y, t)

        params, opt_state, loss = train_step_loop_dist(
            step_fn, params, opt_state, x_all, y_all, args.steps,
            examples_per_step=bs, compile_source=res.source,
            start_step=start_step, checkpoint_every=args.checkpoint_every,
            checkpoint_fn=ck_fn)
        loss = float(loss)
        if mgr is not None:
            # Flush in-flight async saves before anything else reopens the
            # directory (main()'s final save builds a fresh manager).
            mgr.wait()

        ex, ey = replicate_global(
            mesh, np.asarray(eval_set[0]),
            np.asarray(eval_set[1]).astype(np.int32))
        acc = float(jax.jit(m.mlp_accuracy)(params, ex, ey))
    return loss, acc, sp_fit, params, opt_state


if __name__ == "__main__":
    sys.exit(main())
