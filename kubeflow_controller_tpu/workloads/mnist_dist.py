"""Distributed MNIST — the Worker/TPU replica workload.

The reference wires N workers + M parameter servers over grpc and ships
gradients to the PS every step (ref: examples/workdir/mnist_replica.py:
113-141, 251-264).  TPU-native, the PS tier disappears: the worker pods
form ONE jax.distributed cluster (coordinator env injected by the planner,
or derived from ``--worker_hosts`` exactly as the reference workload feeds
tf.train.ClusterSpec), parameters are replicated over the global mesh, and
gradients all-reduce over XLA collectives — one shared model, the same
semantics as the reference's PS training with the grpc data plane replaced
by ICI/gloo (200 steps, batch 100 by default, matching
docs/get_started.md:49-63).

Roles:
- ``ps``: parks forever, the analog of ``server.join()``
  (mnist_replica.py:121-122) — the data plane it used to host now rides
  XLA collectives.
- ``worker`` / TPU replica: joins via jax.distributed (runtime.initialize),
  generates its shard of every global batch on device, trains over the
  global mesh.

The whole workload is ONE compiled program per worker (train_scan_dist):
batch generation, the training scan with a single fused flat-gradient
all-reduce per step, and the sharded eval — where the reference pays one
grpc round-trip per variable per step plus host-side feed_dict staging
(mnist_replica.py:251-264).  On a latency-bound transport the collective
COUNT is the cost model, not the payload size (docs/PERF.md).
"""

from __future__ import annotations

import argparse
import os
import signal
import sys
import time


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description="distributed MNIST")
    # TF-contract args injected by the planner (planner/materialize.py
    # tf_cluster_args; ref: distributed.go:130-162).
    p.add_argument("--job_name", default="")
    p.add_argument("--task_index", type=int, default=-1)
    p.add_argument("--worker_hosts", default="")
    p.add_argument("--ps_hosts", default="")
    p.add_argument("--steps", type=int, default=200)
    p.add_argument("--batch-size", type=int, default=100, help="global batch")
    p.add_argument("--lr", type=float, default=5e-3)
    p.add_argument("--train-size", type=int, default=8192)
    p.add_argument("--eval-size", type=int, default=2048)
    p.add_argument("--target-accuracy", type=float, default=0.0)
    p.add_argument("--platform", default=os.environ.get("WORKLOAD_PLATFORM", ""))
    p.add_argument("--aot-cache", default=os.environ.get("WORKLOAD_AOT_CACHE", ""),
                   help="directory for serialized-executable reuse across "
                        "identical jobs (see trainer.train_scan_dist)")
    args = p.parse_args(argv)

    if args.job_name == "ps":
        # PS data plane replaced by XLA collectives; park until the gang is
        # torn down, like server.join() (the updater ignores PS state for
        # job success — ref: pkg/controller/updater/distributed.go:47-59).
        # sigwait only catches signals that are blocked; unblocked, SIGTERM
        # would run its default disposition and exit 143 instead of 0.
        park = {signal.SIGTERM, signal.SIGINT}
        signal.pthread_sigmask(signal.SIG_BLOCK, park)
        signal.sigwait(park)
        return 0

    if args.platform:
        os.environ["JAX_PLATFORMS"] = args.platform
    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    import jax.numpy as jnp

    from ..models import mnist as m
    from ..obs import trace as obs_trace
    from ..parallel import AXIS_DATA, MeshSpec, build_mesh
    from . import data as d
    from .runtime import JobRuntime
    from .trainer import default_optimizer, numpy_opt_state, train_scan_dist

    # Launch-path phases as obs spans (the single source of truth for the
    # phase breakdown: the "Phase times:" line below and bench.py's
    # --trace-out dump both come from these).
    t_start = time.time()
    with obs_trace.span("workload/rendezvous",
                        task_index=args.task_index) as sp_rdv:
        rt = JobRuntime.from_env()
        rt.merge_tf_args(args.job_name, args.task_index, args.worker_hosts)
        rt.initialize()

    # One global mesh over every process's devices: classic Worker gangs and
    # TPU slices land on the same code path.
    pc, proc = jax.process_count(), jax.process_index()
    with obs_trace.span("workload/init", process=proc) as sp_init:
        mesh = build_mesh(MeshSpec(dp=-1, fsdp=1))

        # Int seed, not PRNGKey: as_seed(PRNGKey(0)) == 0, and building even
        # one key costs a threefry jit compile this process never needs.
        params = m.mlp_init(0)  # same seed -> same init everywhere
        opt = default_optimizer(args.lr)
        # Host-numpy optimizer state (identical to opt.init for the default
        # chain — see trainer.numpy_opt_state): skips the init-time jit
        # cascade that rivals this worker's whole training run.
        opt_state = numpy_opt_state(opt, params)

        # Round the global batch down to a multiple of the data-parallel size
        # (the reference's batch 100 over e.g. 8 devices -> 96 per step).
        dp = mesh.shape[AXIS_DATA]
        bs = max(dp, args.batch_size - args.batch_size % dp)
        local_bs = bs // dp
        # Dataset = train_size samples revisited epoch-by-epoch, regenerated
        # identically on every shard in-program (see synthetic_mnist_traced);
        # each shard slices its columns of every batch.
        spe = max(1, args.train_size // bs)  # steps per epoch
        eval_local = max(1, args.eval_size // dp)
        # Host numpy on purpose: the traced generator closes over it as a
        # compile-time constant; an eager jnp.asarray would pay a device_put
        # plus its tiny-jit before the program even starts.
        means = d.mnist_teacher_means()

        def local_batches(i):
            x, y = d.synthetic_mnist_traced(1, spe * bs, means)
            x = x.reshape(spe, bs, m.IMAGE_PIXELS)
            y = y.reshape(spe, bs)
            return (jax.lax.dynamic_slice_in_dim(x, i * local_bs, local_bs, axis=1),
                    jax.lax.dynamic_slice_in_dim(y, i * local_bs, local_bs, axis=1))

        def eval_counts(p, i):
            ex, ey = d.synthetic_mnist_traced(2, dp * eval_local, means)
            ex = jax.lax.dynamic_slice_in_dim(ex, i * eval_local, eval_local, axis=0)
            ey = jax.lax.dynamic_slice_in_dim(ey, i * eval_local, eval_local, axis=0)
            correct = jnp.sum(jnp.argmax(m.mlp_apply(p, ex), axis=-1) == ey)
            return correct, jnp.asarray(eval_local, jnp.float32)

        aot = ""
        if args.aot_cache:
            os.makedirs(args.aot_cache, exist_ok=True)
            # lr is baked into the compiled program as a constant (the optax
            # chain closes over it), so it MUST be part of the key: two jobs
            # differing only in --lr must not share an executable.
            aot = os.path.join(
                args.aot_cache,
                f"mnist-dist-s{args.steps}-b{bs}-n{args.train_size}"
                f"-e{args.eval_size}-lr{args.lr:g}-dp{dp}-pc{pc}-p{proc}.aot")

    # The whole job — per-step batch generation, the 200-step scan with its
    # single fused all-reduce, and the sharded eval — is ONE compiled
    # program; `fit` below is one dispatch per worker.
    with obs_trace.span("workload/fit", process=proc, steps=args.steps) as sp_fit:
        params, opt_state, loss, acc = train_scan_dist(
            lambda p, b: m.mlp_loss(p, b[0], b[1]), opt, params, opt_state,
            args.steps, mesh, AXIS_DATA, local_batches, eval_counts,
            aot_cache=aot, examples_per_step=bs,
        )
        loss, acc = float(loss), float(acc)
    elapsed = sp_fit.dur

    print(f"Worker {proc}/{pc} on {jax.device_count()} devices "
          f"(mesh dp={dp})")
    # Phase breakdown (bench.py reads the same spans from the trace dump).
    # The phases partition total: rendezvous = jax.distributed join, init =
    # host-side model/optimizer init + means, fit = the single compiled
    # program (trace + cache-load + batch gen + train scan + eval).
    print(f"Phase times: rendezvous={sp_rdv.dur:.3f}s "
          f"init={sp_init.dur:.3f}s "
          f"fit={sp_fit.dur:.3f}s "
          f"total={time.time() - t_start:.3f}s")
    print(f"Training elapsed time: {elapsed:f} s")
    print(f"Final loss: {loss:f}; eval accuracy: {acc:f}")
    # Explicit span dump: warm-forked pods exit via os._exit (no atexit).
    obs_trace.dump_to_env_dir()
    if rt.model_dir:
        from .checkpoint import CheckpointManager

        # Collective under a multi-process mesh: every process participates.
        CheckpointManager(rt.model_dir).save(args.steps, params, opt_state)
        if proc == 0:
            print(f"Checkpoint saved to {rt.model_dir}")
    if args.target_accuracy and acc < args.target_accuracy:
        print(f"accuracy {acc} below target {args.target_accuracy}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
