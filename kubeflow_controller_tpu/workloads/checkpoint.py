"""Checkpoint/resume via Orbax.

The reference declares ``ModelDir`` in the job spec but never reads it
(ref: types.go:46-47, SURVEY.md §5 checkpoint/resume); the controller here
plumbs it into pod env as MODEL_DIR, and this module makes it real: save
params/opt-state/step, restore the latest on restart, so an index-preserved
replacement replica resumes instead of restarting from scratch.
"""

from __future__ import annotations

import logging
import os
import shutil
from typing import Any, Optional, Tuple

import jax

logger = logging.getLogger("kubeflow_controller_tpu.checkpoint")


class CheckpointManager:
    """Small wrapper over orbax-checkpoint with a fixed layout:
    <dir>/<step>/ holds one PyTreeCheckpointer save of
    {"params": ..., "opt_state": ..., "step": int}."""

    def __init__(self, directory: str, keep: int = 3):
        import orbax.checkpoint as ocp

        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(max_to_keep=keep, create=True),
        )

    def save(self, step: int, params: Any, opt_state: Any,
             wait: bool = True) -> None:
        """Durable by default (returns after the write is finalized).  Pass
        wait=False for in-training-loop saves: Orbax serializes in the
        background so the next step overlaps the write; a step only becomes
        visible to latest_step()/restore() once finalized, so resume safety
        is unaffected — but call wait() (or a final wait=True save) before
        declaring success, or a background write failure goes unnoticed."""
        import orbax.checkpoint as ocp

        self._mgr.save(
            step,
            args=ocp.args.StandardSave({"params": params, "opt_state": opt_state}),
        )
        if wait:
            self._mgr.wait_until_finished()

    def wait(self) -> None:
        """Block until every in-flight async save is durable."""
        self._mgr.wait_until_finished()

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def restore(self, target_params: Any, target_opt_state: Any) -> Tuple[Any, Any, int]:
        """Restore the latest *readable* checkpoint onto abstract/like
        targets; returns (params, opt_state, step).  Raises if none exists.

        Shardings are preserved: a target leaf that is a live mesh-sharded
        ``jax.Array`` (the normal case — params are initialized with their
        NamedShardings before restore, e.g. llama_pretrain) restores
        directly into that layout rather than fully-replicated onto default
        devices, which would OOM or mis-place multi-host models on resume.

        Corrupt-checkpoint fallback (the recovery plane's contract): a
        SIGKILL mid-save can leave the newest step dir torn in ways Orbax's
        own finalization marker does not catch (truncated array files, a
        half-written tree).  A step that fails to load is deleted (with one
        warning) and the previous step is tried, so a resuming replica
        degrades to losing one checkpoint interval instead of crash-looping
        on the same bad read forever.
        """
        import orbax.checkpoint as ocp
        from jax.sharding import NamedSharding

        def abstract(x):
            s = getattr(x, "sharding", None)
            if isinstance(s, NamedSharding):
                return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=s)
            return ocp.utils.to_shape_dtype_struct(x)

        ref = {"params": target_params, "opt_state": target_opt_state}
        abstract_ref = jax.tree.map(abstract, ref)
        steps = sorted(self._mgr.all_steps(), reverse=True)
        if not steps:
            raise FileNotFoundError(f"no checkpoint under {self.directory}")
        for i, step in enumerate(steps):
            try:
                restored = self._mgr.restore(
                    step, args=ocp.args.StandardRestore(abstract_ref))
                return restored["params"], restored["opt_state"], step
            except Exception as e:  # noqa: BLE001 — corrupt/partial step
                # (FileNotFoundError here means missing files INSIDE the
                # step dir — torn, not absent; fall back like any corruption.)
                if i + 1 >= len(steps):
                    raise  # nothing older to fall back to
                logger.warning(
                    "checkpoint step %d under %s is unreadable (%s); "
                    "deleting it and falling back to step %d",
                    step, self.directory, e, steps[i + 1])
                self._drop_step(step)
        raise FileNotFoundError(f"no readable checkpoint under {self.directory}")

    # -- elastic width marker -------------------------------------------

    WIDTH_MARKER = "gang_width"

    def read_width(self) -> Optional[int]:
        """The gang width that wrote the checkpoints here (None = never
        recorded).  A restore under a DIFFERENT runtime width is a
        re-shard: data shards rebalance and the workload beats
        ``phase="reshard"`` so the stall detector holds its frozen-step
        deadline through the transition."""
        try:
            with open(os.path.join(self.directory, self.WIDTH_MARKER)) as fh:
                return int(fh.read().strip() or "0") or None
        except (OSError, ValueError):
            return None

    def write_width(self, width: int) -> None:
        """Record the writing gang's width (process 0 only; atomic
        tmp+rename so a kill mid-write never leaves a torn marker)."""
        path = os.path.join(self.directory, self.WIDTH_MARKER)
        tmp = f"{path}.tmp.{os.getpid()}"
        try:
            with open(tmp, "w") as fh:
                fh.write(str(width))
            os.replace(tmp, path)
        except OSError:
            pass  # marker is advisory; restore falls back to "restore"

    def _drop_step(self, step: int) -> None:
        """Remove a bad step so no later resume trips over it again (the
        manager's own delete first; rmtree as the fallback for dirs the
        manager no longer recognizes)."""
        try:
            self._mgr.delete(step)
            return
        except Exception:  # noqa: BLE001
            pass
        shutil.rmtree(os.path.join(self.directory, str(step)),
                      ignore_errors=True)
