"""Checkpoint/resume via Orbax.

The reference declares ``ModelDir`` in the job spec but never reads it
(ref: types.go:46-47, SURVEY.md §5 checkpoint/resume); the controller here
plumbs it into pod env as MODEL_DIR, and this module makes it real: save
params/opt-state/step, restore the latest on restart, so an index-preserved
replacement replica resumes instead of restarting from scratch.
"""

from __future__ import annotations

import os
from typing import Any, Optional, Tuple

import jax


class CheckpointManager:
    """Small wrapper over orbax-checkpoint with a fixed layout:
    <dir>/<step>/ holds one PyTreeCheckpointer save of
    {"params": ..., "opt_state": ..., "step": int}."""

    def __init__(self, directory: str, keep: int = 3):
        import orbax.checkpoint as ocp

        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(max_to_keep=keep, create=True),
        )

    def save(self, step: int, params: Any, opt_state: Any,
             wait: bool = True) -> None:
        """Durable by default (returns after the write is finalized).  Pass
        wait=False for in-training-loop saves: Orbax serializes in the
        background so the next step overlaps the write; a step only becomes
        visible to latest_step()/restore() once finalized, so resume safety
        is unaffected — but call wait() (or a final wait=True save) before
        declaring success, or a background write failure goes unnoticed."""
        import orbax.checkpoint as ocp

        self._mgr.save(
            step,
            args=ocp.args.StandardSave({"params": params, "opt_state": opt_state}),
        )
        if wait:
            self._mgr.wait_until_finished()

    def wait(self) -> None:
        """Block until every in-flight async save is durable."""
        self._mgr.wait_until_finished()

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def restore(self, target_params: Any, target_opt_state: Any) -> Tuple[Any, Any, int]:
        """Restore the latest checkpoint onto abstract/like targets; returns
        (params, opt_state, step).  Raises if none exists.

        Shardings are preserved: a target leaf that is a live mesh-sharded
        ``jax.Array`` (the normal case — params are initialized with their
        NamedShardings before restore, e.g. llama_pretrain) restores
        directly into that layout rather than fully-replicated onto default
        devices, which would OOM or mis-place multi-host models on resume.
        """
        import orbax.checkpoint as ocp
        from jax.sharding import NamedSharding

        step = self._mgr.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoint under {self.directory}")

        def abstract(x):
            s = getattr(x, "sharding", None)
            if isinstance(s, NamedSharding):
                return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=s)
            return ocp.utils.to_shape_dtype_struct(x)

        ref = {"params": target_params, "opt_state": target_opt_state}
        restored = self._mgr.restore(
            step, args=ocp.args.StandardRestore(jax.tree.map(abstract, ref))
        )
        return restored["params"], restored["opt_state"], step
