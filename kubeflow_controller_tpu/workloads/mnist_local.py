"""Single-process MNIST — the Local replica workload.

Parity target: the reference's local example trains softmax regression and
prints accuracy (ref: examples/workdir/mnist_softmax.py:44-72,
docs/get_started.md:29-38 "0.9234 after 100k steps").  Run as the pod
command by the kubelet's execute mode; exits 0 on success so the pod (and
the job) reach Succeeded.
"""

from __future__ import annotations

import argparse
import os
import sys
import time


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description="local MNIST")
    p.add_argument("--model", choices=["softmax", "mlp"], default="mlp")
    p.add_argument("--steps", type=int, default=200)
    p.add_argument("--batch-size", type=int, default=100)
    p.add_argument("--lr", type=float, default=5e-3)
    p.add_argument("--eval-size", type=int, default=2048)
    p.add_argument("--train-size", type=int, default=8192)
    p.add_argument("--target-accuracy", type=float, default=0.0,
                   help="exit non-zero if final accuracy is below this")
    p.add_argument("--platform", default=os.environ.get("WORKLOAD_PLATFORM", ""),
                   help="force a jax platform (cpu/tpu); default: leave as is")
    args = p.parse_args(argv)

    if args.platform:
        os.environ["JAX_PLATFORMS"] = args.platform
    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    import jax.numpy as jnp

    from ..models import mnist as m
    from . import data as d
    from .runtime import JobRuntime
    from .trainer import batch_stack, default_optimizer, train_scan

    rt = JobRuntime.from_env()
    key = jax.random.PRNGKey(0)
    x, y = d.synthetic_mnist(jax.random.PRNGKey(1), args.train_size)
    ex, ey = d.synthetic_mnist(jax.random.PRNGKey(2), args.eval_size)

    if args.model == "softmax":
        params = m.softmax_init(key)
        apply_fn = m.softmax_apply
    else:
        params = m.mlp_init(key)
        apply_fn = m.mlp_apply

    opt = default_optimizer(args.lr)
    opt_state = opt.init(params)

    start = time.time()
    batches = batch_stack(x, y, args.steps, args.batch_size)
    params, opt_state, loss = train_scan(
        lambda p, b: m.mlp_loss(p, b[0], b[1], apply_fn=apply_fn),
        opt, params, opt_state, batches,
    )
    loss = float(loss)
    elapsed = time.time() - start

    # Join the job's causal trace ($KCTPU_TRACE_CONTEXT, injected by the
    # planner): one span for the whole compiled run, dumped explicitly
    # because warm-forked pods exit through os._exit (no atexit).
    from ..obs import trace as obs_trace

    obs_trace.add_span("workload/train", start, elapsed,
                       ctx=obs_trace.current_context(), steps=args.steps)
    obs_trace.dump_to_env_dir()

    acc = float(m.mlp_accuracy(params, ex, ey, apply_fn=apply_fn))
    # Same sign-off line format as the reference workload
    # (ref: examples/workdir/mnist_replica.py:263 "Training elapsed time").
    print(f"Training elapsed time: {elapsed:f} s")
    print(f"Final loss: {loss:f}; eval accuracy: {acc:f}")
    if rt.model_dir:
        from .checkpoint import CheckpointManager

        CheckpointManager(rt.model_dir).save(args.steps, params, opt_state)
        print(f"Checkpoint saved to {rt.model_dir}")
    if args.target_accuracy and acc < args.target_accuracy:
        print(f"accuracy {acc} below target {args.target_accuracy}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
