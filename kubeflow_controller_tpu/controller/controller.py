"""The reconcile engine: informers -> workqueue -> sync -> planner -> writes.

Semantic successor of pkg/controller/controller.go (the 649-line heart of the
reference), preserving its level-triggered architecture:

- three informers (TFJob, Pod, Service) feed a rate-limited workqueue with
  ``namespace/name`` keys (ref: controller.go:98-165);
- per-key serialization via the queue's dirty/processing discipline
  (ref: controller.go:72-76);
- the expectations cache guards the create/observe race
  (ref: controller.go:278, 373-443);
- ``run(threadiness)`` waits for cache sync then spawns workers in
  get/sync/done loops with Forget-on-success / requeue-with-backoff
  (ref: controller.go:174-259).

Deliberate upgrades over the reference (each cited gap is from SURVEY.md):

- pod/service **delete handlers are implemented** (stubs upstream,
  controller.go:522-524, 601-603): deletions feed expectations and re-queue
  the owner, so failed/vanished replicas are replaced;
- the stamped ``runtime_id`` is **persisted** to the job spec before any
  replica is created (upstream stamps it in-memory per sync, local.go:79-84);
- status updates go through the status subresource with conflict retries
  (upstream does a bare full-object Update, controller.go:643-649);
- TPU jobs release their slice gang on terminal cleanup (net-new);
- reconcile latency is measured per sync (the BASELINE reconcile-p50 metric).
"""

from __future__ import annotations

import logging
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional

from ..api.core import Pod, Service
from ..api.labels import LABEL_JOB_TYPE, job_selector
from ..api.meta import get_controller_of, key_of, split_key
from ..api.tenant import tenant_of
from ..api.tfjob import (
    KIND,
    JobGoodput,
    ReplicaType,
    TFJob,
    TFJobPhase,
    ValidationError,
    is_tpu_job,
    replica_spec_for,
    validate_tfjob,
)
from ..checker import StallPolicy, StallTracker
from ..cluster.client import Cluster
from ..cluster.store import Conflict, NotFound
from ..cluster.tpu import TPUInventory
from ..obs import trace
from ..obs.goodput import (
    ANNOTATION_START_MODE,
    GoodputTracker,
    PodObservation,
)
from ..obs.lifecycle import job_lifecycle
from ..obs.metrics import REGISTRY
from ..obs.phases import (
    POD_REASON_PREEMPTED_PREFIX,
    POD_REASON_QUEUED_PREFIX,
)
from ..planner import plan_job
from ..planner.materialize import (
    gang_name,
    make_pod,
    make_service,
    pod_index,
    trace_context_for,
)
from ..planner.types import Action
from ..updater import RollupCache, compute_status, should_update
from ..utils import locks, serde
from ..utils.names import generate_runtime_id
from ..recovery.policy import (
    ACTION_BACKOFF,
    ACTION_EXHAUSTED,
    ACTION_REPLACE,
    RestartPolicyConfig,
    RestartTracker,
)
from ..elastic import ElasticEngine, ElasticPolicy
from ..serving import ServingAutoscaler
from .events import (
    EventRecorder,
    REASON_BACKOFF_LIMIT_EXCEEDED,
    REASON_GANG_ADMITTED,
    REASON_GANG_DEGRADED,
    REASON_GANG_PREEMPTED,
    REASON_GANG_QUEUED,
    REASON_GANG_RESTORED,
    REASON_REPLICA_RESTARTED,
    REASON_SERVING_DRAINING,
    REASON_SERVING_SCALED_DOWN,
    REASON_SERVING_SCALED_UP,
    REASON_SLO_BURN,
    REASON_SLO_RECOVERED,
    REASON_TRAINING_RESUMED,
    REASON_TRAINING_STALLED,
    TYPE_NORMAL,
    TYPE_WARNING,
)
from .expectations import ControllerExpectations
from .helper import Helper, register_gather_indexers
from .informer import SharedInformer
from .metrics import ReconcileMetrics
from .slowstart import ManageError, slow_start_batch
from .workqueue import RateLimitingQueue, ShutDown

logger = logging.getLogger("kubeflow_controller_tpu.controller")

MAX_STATUS_RETRIES = 5

# Finalizer guarding explicit child cleanup on TFJob deletion.
FINALIZER = "kubeflow.caicloud.io/tfjob-cleanup"


class Controller:
    def __init__(
        self,
        cluster: Cluster,
        inventory: Optional[TPUInventory] = None,
        resync_period_s: float = 30.0,
        recorder: Optional[EventRecorder] = None,
        stall_policy: Optional[StallPolicy] = None,
        manage_workers: int = 8,
        restart_config: Optional[RestartPolicyConfig] = None,
        controller_shards: int = 1,
        elastic_policy: Optional[ElasticPolicy] = None,
    ):
        self.cluster = cluster
        self.inventory = inventory
        # HA sharding (ha/shards.py): with controller_shards > 1 the
        # single workqueue becomes a consistent-hash-routed queue per
        # shard worker — each job's syncs stay on one shard (per-job
        # ordering), shards progress independently (the --scale
        # parallelism bench.py --ha gates), and set_controller_shards()
        # rebalances with a draining handoff.
        self.controller_shards = max(1, controller_shards)
        # Plan-execution fan-out: ``manage_workers`` bounds the threads that
        # issue child create/delete calls concurrently (the slow-start
        # batches in _manage_inner).  <=1 selects the serial path — the
        # baseline `bench.py --replicas N --manage-workers 1` measures
        # against.  The pool is lazy (most tests never manage wide plans)
        # and shared across sync workers, so total write concurrency per
        # controller is bounded regardless of threadiness.
        self.manage_workers = manage_workers
        self._manage_pool: Optional[ThreadPoolExecutor] = None
        self._manage_pool_lock = locks.named_lock("controller.manage-pool")
        self._h_batch = REGISTRY.histogram(
            "kctpu_manage_batch_size",
            "Plan events dispatched per slow-start batch",
            buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512))
        # Training-plane stall detection: per-pod step-advancement memory
        # + the deadlines that turn a silent heartbeat into Degraded
        # health, a TrainingStalled event, and kctpu_job_stalled=1.
        self.stall_policy = stall_policy or StallPolicy()
        self.stall_tracker = StallTracker(self.stall_policy)
        # Recovery plane: per-replica restart accounting with exponential
        # backoff + jitter and a backoffLimit -> terminal Failed
        # (recovery/policy.py).  The tracker gates the planner's
        # index-preserving replacement path and feeds the RESTARTS column,
        # ReplicaRestarted/BackoffLimitExceeded events, and the
        # kctpu_replica_restarts_total / restart-latency metrics.
        self.restart_tracker = RestartTracker(restart_config)
        # Elastic plane: the width transition engine (elastic/engine.py).
        # For jobs with spec.elastic, member loss becomes a re-shard to
        # reduced width (training continues from the latest checkpoint
        # while the replacement warms) and a later re-expand back to full
        # width — instead of the whole gang stalling behind one index's
        # backoff.  The scheduler's width harvesting funnels through the
        # same transition (WidthHarvested pod reasons).
        self.elastic_engine = ElasticEngine(elastic_policy)
        # Per-job stalled-replica set from the LAST sync, for edge-triggered
        # TrainingStalled/TrainingResumed events (the condition itself is
        # level-triggered in status).
        self._stalled: Dict[str, frozenset] = {}
        self._stalled_lock = locks.named_lock("controller.stalled")
        # Per-job gang scheduling state ("queued"/"admitted"/"preempted")
        # from the LAST sync, for edge-triggered GangQueued/GangAdmitted/
        # GangPreempted events (shares the stalled lock — same cadence).
        self._gang_state: Dict[str, str] = {}
        # Goodput ledger (obs/goodput.py): every sync's observed pods are
        # folded into per-job phase-attributed time accounting; the
        # quantized rollup lands on status.goodput at most once per
        # ``goodput_status_interval_s`` (plus the terminal edge) so the
        # ticking seconds don't force a status write per sync.  The
        # per-key last-attach time shares the stalled lock (same cadence).
        self.goodput_tracker = GoodputTracker()
        self.goodput_status_interval_s = 15.0
        self._goodput_pub: Dict[str, float] = {}
        # Serving plane: the queue-depth autoscaler (serving/autoscale.py)
        # and the per-job set of replica indices whose serving gauge
        # series are live — scale-down calls Gauge.remove for indices
        # that left, freeing the metric series budget (shares the
        # stalled lock — same cadence).
        self.serving_autoscaler = ServingAutoscaler()
        self._serving_series: Dict[str, frozenset] = {}
        self._g_serve_queue = REGISTRY.gauge(
            "kctpu_serve_queue_depth",
            "Serving replica intake-queue depth (requests waiting for a "
            "batch slot)", ("namespace", "tfjob", "replica"))
        self._g_serve_occ = REGISTRY.gauge(
            "kctpu_serve_batch_occupancy",
            "Serving replica batch occupancy (slots in use / slots)",
            ("namespace", "tfjob", "replica"))
        self._g_serve_qps = REGISTRY.gauge(
            "kctpu_serve_qps",
            "Job-level serving throughput (completed requests/sec summed "
            "across ready replicas)", ("namespace", "tfjob"))
        self._g_serve_ttft = REGISTRY.gauge(
            "kctpu_serve_ttft_ms",
            "Worst replica's windowed p50 time-to-first-token",
            ("namespace", "tfjob"))
        self._g_serve_ttft_p99 = REGISTRY.gauge(
            "kctpu_serve_ttft_p99_ms",
            "Worst replica's windowed p99 time-to-first-token (what the "
            "serving-ttft-p99 SLO burns against)", ("namespace", "tfjob"))
        self._g_serve_replicas = REGISTRY.gauge(
            "kctpu_serve_replicas",
            "Current Serving replica target (the autoscaler-written "
            "serving-replicas annotation)", ("namespace", "tfjob"))
        self._g_serve_ready = REGISTRY.gauge(
            "kctpu_serve_replicas_ready",
            "Serving replicas past model load + first decode step",
            ("namespace", "tfjob"))
        self._c_serve_scale = REGISTRY.counter(
            "kctpu_serve_scale_events_total",
            "Autoscaler target changes by direction", ("direction",))
        # Job-level progress gauges (namespace+job labels; series removed
        # on job deletion — see _drop_progress_series).
        self._g_step = REGISTRY.gauge(
            "kctpu_job_step",
            "Job-level training step (min across reporting replicas)",
            ("namespace", "tfjob"))
        self._g_rate = REGISTRY.gauge(
            "kctpu_job_examples_per_sec",
            "Job-level training throughput (sum across reporting replicas)",
            ("namespace", "tfjob"))
        self._g_stalled = REGISTRY.gauge(
            "kctpu_job_stalled",
            "1 when any replica's training heartbeat/step is stalled",
            ("namespace", "tfjob"))
        self._g_lag = REGISTRY.gauge(
            "kctpu_job_straggler_lag_steps",
            "Straggler lag: max step minus min step across replicas",
            ("namespace", "tfjob"))
        # Default recorder writes real Event API objects (kubectl-describe
        # visibility) in addition to the in-memory/log stream.  We only own
        # (and thus close) a recorder we created.
        self._owns_recorder = recorder is None
        self.recorder = recorder or EventRecorder(
            sink=getattr(cluster, "events", None))
        # Key -> tenant cache for the workqueue's per-tenant fresh tier,
        # filled from watch edges (the label-aware tenant, not just the
        # namespace).  Plain dict: single-item get/set only.
        self._tenant_by_key: Dict[str, str] = {}
        if self.controller_shards > 1:
            from ..ha.shards import ShardedWorkQueue

            self.queue = ShardedWorkQueue(
                self.controller_shards, name="tfJobs",
                uid_fn=self._shard_uid, on_handoff=self._on_shard_handoff,
                tenant_of=self._tenant_for_key)
        else:
            self.queue = RateLimitingQueue(name="tfJobs",
                                           tenant_of=self._tenant_for_key)
        self.expectations = ControllerExpectations()
        self.metrics = ReconcileMetrics()
        # Incremental rollup: memoizes compute_status per job, keyed by the
        # RVs of every input (job, observed pods, recovery verdicts), so a
        # level-triggered re-pass over an unmoved world skips the rollup
        # AND the should_update double-serialization (updater/incremental).
        self.rollup_cache = RollupCache()
        # Prometheus surface: reconcile latency quantiles + op counters land
        # on the process-global registry (served at GET /metrics).
        self.metrics.register()

        self.tfjob_informer = SharedInformer(cluster.tfjobs, resync_period_s, "tfjobs")
        self.pod_informer = SharedInformer(cluster.pods, resync_period_s, "pods")
        self.service_informer = SharedInformer(cluster.services, resync_period_s, "services")
        # Owner-UID + job-selector indices: what makes a steady-state gather
        # O(own children) instead of a full-namespace LIST (helper.py).
        register_gather_indexers(self.pod_informer)
        register_gather_indexers(self.service_informer)
        self.helper = Helper(cluster, self.recorder,
                             pod_informer=self.pod_informer,
                             service_informer=self.service_informer,
                             metrics=self.metrics)

        # TFJob events all funnel into the queue (ref: controller.go:138-153).
        self.tfjob_informer.add_event_handler(
            on_add=self._enqueue,
            on_update=self._on_tfjob_update,
            on_delete=self._on_tfjob_delete,
        )
        # Pod/Service feedback edges (ref: controller.go:447-599 + the
        # upstream-stubbed delete handlers, implemented here).
        self.pod_informer.add_event_handler(
            on_add=lambda p: self._on_child_add(p),
            on_update=lambda old, new: self._on_child_update(old, new),
            on_delete=lambda p: self._on_child_delete(p),
        )
        self.service_informer.add_event_handler(
            on_add=lambda s: self._on_child_add(s),
            on_update=lambda old, new: self._on_child_update(old, new),
            on_delete=lambda s: self._on_child_delete(s),
        )
        # Tenant fair-share contracts: mirror TenantQuota specs into the
        # scheduler's DRF ledger (live weight changes re-key its share
        # heap on the next admission pass).  Wired only when the cluster
        # exposes the collection and the inventory is scheduler-shaped —
        # a bare TPUInventory has no ledger and needs no watch.
        self.tenantquota_informer = None
        tq_client = getattr(cluster, "tenantquotas", None)
        if tq_client is not None and hasattr(inventory, "set_tenant_quota"):
            self.tenantquota_informer = SharedInformer(
                tq_client, resync_period_s, "tenantquotas")
            self.tenantquota_informer.add_event_handler(
                on_add=self._on_tenantquota_set,
                on_update=lambda old, new: self._on_tenantquota_set(new),
                on_delete=self._on_tenantquota_delete,
            )

        self._workers: List[threading.Thread] = []
        self._stop = threading.Event()
        # Observability plane (started on demand by start_obs_plane):
        # TSDB sampler + SLO burn evaluation + flight recorder.  The
        # once-per-key guard keeps a Failed job that keeps resyncing from
        # cutting a new postmortem bundle every pass.
        self._tsdb = None
        self._slo_engine = None
        self._flight_cut: set = set()
        self._flight_lock = locks.named_lock("controller.flight")

    # ------------------------------------------------------------------ run

    def run(self, threadiness: int = 2, wait_sync_timeout: float = 10.0) -> None:
        """Start informers, wait for cache sync, spawn workers
        (ref: controller.go:174-198; threadiness=2 at main.go:70)."""
        logger.info("starting TFJob controller")
        self._threadiness = threadiness
        infs = [self.tfjob_informer, self.pod_informer, self.service_informer]
        if self.tenantquota_informer is not None:
            infs.append(self.tenantquota_informer)
        for inf in infs:
            inf.start()
        for inf in infs:
            if not inf.wait_for_cache_sync(wait_sync_timeout):
                raise TimeoutError(f"timed out waiting for {inf.name} cache sync")
        if self.controller_shards > 1:
            # Sharded mode: `threadiness` workers PER shard, each pinned
            # to its shard's queue (per-job ordering within a shard, full
            # parallelism across shards).
            for s in range(self.controller_shards):
                self._spawn_shard_workers(s)
        else:
            for i in range(threadiness):
                t = threading.Thread(target=self._worker,
                                     name=f"tfjob-worker-{i}", daemon=True)
                self._workers.append(t)
                t.start()
        # Stall timer: a stalled pod, by definition, generates no watch
        # events, so progressing jobs are re-enqueued on a clock — the
        # level-triggered backstop that lets the stall deadline actually
        # fire (resync would too, but 30 s is far too coarse for training
        # liveness).
        t = threading.Thread(target=self._stall_loop, name="stall-timer",
                             daemon=True)
        self._workers.append(t)
        t.start()
        logger.info("started %d workers", threadiness)

    def _stall_loop(self) -> None:
        interval = self.stall_policy.effective_check_interval()
        while not self._stop.wait(interval):
            for job in self.tfjob_informer.list():
                if job.status.phase in (TFJobPhase.SUCCEEDED, TFJobPhase.FAILED):
                    continue
                if job.status.progress is None:
                    continue  # never reported: nothing to watch for silence
                # Low tier: a liveness re-check must never queue ahead of
                # the watch-edge work that actually advances jobs.
                self.queue.add(key_of(job.metadata), low=True)

    def start_obs_plane(self, interval_s: float = 1.0) -> None:
        """Host the cluster observability plane in this controller: start
        the process-global TSDB sampling /metrics on a cadence, hang the
        SLO engine off its sampler (burn evaluation rides every sample
        pass), and route burn edges to the event recorder as
        ``Warning SLOBurn`` / ``Normal SLORecovered`` on the breaching
        job.  Idempotent; opt-in because tests and small tools that build
        a Controller shouldn't pay for a sampler thread."""
        from ..obs.slo import default_slo_engine
        from ..obs.tsdb import default_tsdb

        if self._tsdb is not None:
            return
        self._tsdb = default_tsdb()
        self._tsdb.interval_s = interval_s
        self._slo_engine = default_slo_engine()
        self._slo_engine.set_notifier(self._notify_slo)
        self._tsdb.add_listener(self._slo_engine.evaluate_once)
        self._tsdb.start()

    def _notify_slo(self, state, fired: bool) -> None:
        """Burn edge -> Event on the job the breaching series belongs to
        (cluster-scoped objectives fall back to a pseudo-object so the
        edge still lands in the audit stream)."""
        labels = state.labels
        ns, name = labels.get("namespace", ""), labels.get("tfjob", "")
        obj = self.tfjob_informer.get(ns, name) if ns and name else None
        if obj is None:
            from ..api.meta import ObjectMeta

            class _ClusterSLO:
                kind = "SLO"
                metadata = ObjectMeta(namespace=ns or "cluster",
                                      name=name or state.objective.name)
            obj = _ClusterSLO()
        o = state.objective
        if fired:
            self.recorder.event(
                obj, TYPE_WARNING, REASON_SLO_BURN,
                f"SLO {o.name} burning: {o.metric}={state.value:.4g} vs "
                f"threshold {o.threshold:g} (fast burn "
                f"{state.burn_fast:.1f}x, slow {state.burn_slow:.1f}x "
                f">= {o.burn_threshold:g}x budget)")
        else:
            self.recorder.event(
                obj, TYPE_NORMAL, REASON_SLO_RECOVERED,
                f"SLO {o.name} recovered: fast-window burn "
                f"{state.burn_fast:.1f}x back under {o.burn_threshold:g}x")

    def stop(self) -> None:
        self._stop.set()
        if self._tsdb is not None:
            self._tsdb.stop()
        if self._slo_engine is not None:
            self._slo_engine.set_notifier(None)
        self.queue.shut_down()
        infs = [self.tfjob_informer, self.pod_informer, self.service_informer]
        if self.tenantquota_informer is not None:
            infs.append(self.tenantquota_informer)
        for inf in infs:
            inf.stop()
        with self._manage_pool_lock:
            pool, self._manage_pool = self._manage_pool, None
        if pool is not None:
            pool.shutdown(wait=False)
        if self._owns_recorder:
            self.recorder.close()  # drain pending Event API writes

    def _spawn_shard_workers(self, shard: int) -> None:
        for i in range(getattr(self, "_threadiness", 1)):
            t = threading.Thread(target=self._worker, args=(shard,),
                                 name=f"tfjob-worker-s{shard}-{i}",
                                 daemon=True)
            self._workers.append(t)
            t.start()

    def set_controller_shards(self, n: int) -> None:
        """Rebalance the shard ring to ``n`` workers: pending + delayed
        work is handed off through the new routing after in-flight syncs
        drain, moved jobs' expectations are replayed (ha/shards.py), and
        workers are spawned for new shards / retired shards' workers exit
        on their queue's ShutDown."""
        if self.controller_shards <= 1:
            raise RuntimeError("controller was not built with "
                               "controller_shards > 1")
        new_idx = self.queue.set_shards(n)
        self.controller_shards = n
        if not self._stop.is_set():
            for s in new_idx:
                self._spawn_shard_workers(s)

    def _shard_uid(self, key: str) -> Optional[str]:
        """Ring identity for a job key: its UID (the partition domain the
        CLI's shard_of display shares); None until the informer knows it."""
        ns, name = split_key(key)
        job = self.tfjob_informer.get(ns, name)
        return job.metadata.uid if job is not None else None

    def _on_shard_handoff(self, key: str) -> None:
        """A job moved shards: replay its expectations so the new owner's
        first sync re-plans from the observed world instead of trusting
        in-flight counts accumulated by the old shard (whose pending
        watch events may have raced the handoff)."""
        self.expectations.delete_expectations(key)

    def _worker(self, shard: Optional[int] = None) -> None:
        while not self._stop.is_set():
            try:
                self._process_next_work_item(shard)
            except ShutDown:
                return
            except Exception:  # the worker itself must never die
                logger.exception("unhandled error in worker loop")

    def _process_next_work_item(self, shard: Optional[int] = None) -> None:
        """ref: controller.go:210-259."""
        if shard is None:
            key = self.queue.get(timeout=0.5)
        else:
            key = self.queue.get_shard(shard, timeout=0.5)
        if key is None:
            return
        t0 = time.monotonic()
        error = False
        try:
            self.sync_handler(key)
            self.queue.forget(key)
        except Exception as e:
            error = True
            logger.warning("error syncing %s (requeue #%d): %s",
                           key, self.queue.num_requeues(key), e)
            self.queue.add_rate_limited(key)
        finally:
            self.queue.done(key)
            self.metrics.record_sync(time.monotonic() - t0, error=error)

    # --------------------------------------------------------------- events

    def _tenant_for_key(self, key: str) -> str:
        """Workqueue tenant resolver: the label-aware tenant cached off
        the job's watch edges, else the key's namespace (the same default
        api/tenant.tenant_of applies)."""
        t = self._tenant_by_key.get(key)
        if t:
            return t
        return key.split("/", 1)[0] if "/" in key else "default"

    def _on_tenantquota_set(self, quota) -> None:
        spec = quota.spec
        self.inventory.set_tenant_quota(
            quota.metadata.name, weight=spec.weight, slices=spec.slices,
            serving_replicas=spec.serving_replicas,
            borrowable=spec.borrowable)

    def _on_tenantquota_delete(self, quota) -> None:
        self.inventory.remove_tenant_quota(quota.metadata.name)

    def _enqueue(self, job: TFJob) -> None:
        key = key_of(job.metadata)
        self._tenant_by_key[key] = tenant_of(job)
        self.queue.add(key)

    def _on_tfjob_update(self, old: TFJob, new: TFJob) -> None:
        """Enqueue on real edges; on same-RV resyncs (the level-triggered
        backstop, ref: controller.go:480-484) skip jobs that are settled:
        terminal phase, not deleting, expectations satisfied.  A Succeeded
        job would otherwise be re-gathered every resync period forever —
        pure churn that scales with completed-job count.  Unsettled resyncs
        ride the workqueue's LOW tier: a periodic backstop pass must never
        delay the fresh watch edges behind it in a 10k-job storm."""
        if old.metadata.resource_version == new.metadata.resource_version:
            if (
                new.status.phase in (TFJobPhase.SUCCEEDED, TFJobPhase.FAILED)
                and new.metadata.deletion_timestamp is None
                and self.expectations.satisfied_expectations(key_of(new.metadata))
            ):
                return
            self.queue.add(key_of(new.metadata), low=True)
            return
        self._enqueue(new)

    def _on_tfjob_delete(self, job: TFJob) -> None:
        key = key_of(job.metadata)
        self.expectations.delete_expectations(key)
        self.restart_tracker.forget_job(key)
        self.elastic_engine.forget_job(key, job)
        self.rollup_cache.forget(key)
        self._drop_progress_series(key, job)
        self._drop_serving_series(key, job)
        self._drop_goodput(key)
        if self.inventory is not None and is_tpu_job(job):
            self.inventory.release_gang(gang_name(job))
        self.queue.add(key)  # final sync performs cleanup if needed
        # Drop the tenant cache AFTER the final add: the queue resolves
        # tenancy at push time, so the final sync still files correctly.
        self._tenant_by_key.pop(key, None)

    def _resolve_controller_ref(self, obj) -> Optional[str]:
        """ref: resolveControllerRef at controller.go:608-624 — owner key iff
        the ref points at a live TFJob whose UID matches."""
        ref = get_controller_of(obj.metadata)
        if ref is None or ref.kind != KIND:
            return None
        job = self.tfjob_informer.get(obj.metadata.namespace, ref.name)
        if job is None or job.metadata.uid != ref.uid:
            return None
        return key_of(job.metadata)

    def _on_child_add(self, obj) -> None:
        """ref: addPod/addService at controller.go:447-471, 526-547."""
        if obj.metadata.deletion_timestamp is not None:
            self._on_child_delete(obj)
            return
        key = self._resolve_controller_ref(obj)
        if key is None:
            return
        self.expectations.creation_observed(key)
        self.queue.add(key)

    def _on_child_update(self, old, new) -> None:
        """ref: updatePod at controller.go:474-520 — ignore same-RV resyncs
        of children; notify both old and new owners on ref change."""
        if old.metadata.resource_version == new.metadata.resource_version:
            return
        old_ref = get_controller_of(old.metadata)
        new_ref = get_controller_of(new.metadata)
        if old_ref is not None and (new_ref is None or old_ref.uid != new_ref.uid):
            old_job = self.tfjob_informer.get(old.metadata.namespace, old_ref.name)
            if old_job is not None:
                self.queue.add(key_of(old_job.metadata))
        key = self._resolve_controller_ref(new)
        if key is not None:
            self.queue.add(key)

    def _on_child_delete(self, obj) -> None:
        """The handler the reference left "To Be Implemented"
        (controller.go:522-524, 601-603)."""
        key = self._resolve_controller_ref(obj)
        if key is None:
            return
        self.expectations.deletion_observed(key)
        self.queue.add(key)

    # ----------------------------------------------------------------- sync

    def sync_handler(self, key: str) -> None:
        """ref: syncTFJob at controller.go:264-357.  The whole sync runs
        under a trace span; gather/manage/update_status nest inside it, so
        a slow reconcile decomposes in the dump instead of being one
        opaque latency sample."""
        with trace.span("sync", key=key):
            self._sync(key)

    def _sync(self, key: str) -> None:
        ns, name = split_key(key)
        job = self.tfjob_informer.get(ns, name)
        if job is None:
            # Deleted: expectations cleaned in the delete handler; cascade GC
            # removes children server-side.  Serving gauges drop HERE too —
            # this sync is per-key-ordered after any publish that raced the
            # delete handler's first drop.
            self._drop_serving_series(key)
            self._drop_goodput(key)
            self.expectations.delete_expectations(key)
            if self.controller_shards > 1:
                # Final sync of a dead job, running on its owning shard:
                # the cached ring identity (its UID) can be dropped now —
                # a recreated same-name job routes by its own fresh UID.
                self.queue.forget_route(key)
            return
        # Never mutate the informer cache (the reference mutates lister
        # objects — the shared-template bug class).
        job = serde.deep_copy(job)

        # Causal trace: every span this sync records (gather, manage,
        # slow-start batches, status write) joins the job's trace — the
        # context is deterministic from the job UID, so controller spans
        # and workload spans agree on the trace id with no handshake.
        with trace.context(trace_context_for(job)):
            self._sync_job(key, ns, name, job)

    def _sync_job(self, key: str, ns: str, name: str, job: TFJob) -> None:
        deleting = job.metadata.deletion_timestamp is not None

        # Finalizer-based cleanup, replacing reliance on server-side cascade
        # (which real CRD deployments may lack): every live job carries our
        # finalizer; a deleting job is cleaned up explicitly — release the
        # gang, delete children — and only then is the finalizer removed so
        # the API server finalizes the object (ref: the delete handlers the
        # reference stubbed at controller.go:522-524, 601-603).  This runs
        # BEFORE validation: a job whose spec went invalid after creation
        # must still be deletable, or it lingers forever.
        if deleting:
            with trace.span("sync/finalize", key=key):
                self._finalize_job(key, job)
            return

        try:
            validate_tfjob(job)
        except ValidationError as e:
            self.recorder.event(job, TYPE_WARNING, "InvalidSpec", str(e))
            return  # do not requeue: the spec must change first

        if FINALIZER not in job.metadata.finalizers:
            from ..api.labels import ANNOTATION_TRACE_CONTEXT

            # Piggyback the trace-context annotation on the finalizer
            # patch (one write, not two): from here on every pod the
            # planner stamps and every CLI read shares the job's trace id.
            ctx = trace.current_context()
            encoded = ctx.encode() if ctx is not None else ""

            def add_finalizer(m):
                if FINALIZER not in m.finalizers and m.deletion_timestamp is None:
                    m.finalizers.append(FINALIZER)
                if encoded and ANNOTATION_TRACE_CONTEXT not in m.annotations:
                    m.annotations[ANNOTATION_TRACE_CONTEXT] = encoded

            try:
                # Continue the sync with the patched object: its bumped
                # resourceVersion would otherwise Conflict the runtime-ID
                # update below on every new job's first sync.
                job = self.cluster.tfjobs.patch_meta(ns, name, add_finalizer)
            except NotFound:
                return
            if ctx is not None:
                # Root of the causal tree: the submit->first-sync interval,
                # emitted exactly once (first sync stamps the finalizer).
                now = time.time()
                created = job.metadata.creation_timestamp or now
                trace.add_span("job/submit", created, max(0.0, now - created),
                               ctx=ctx, span_id=ctx.span_id,
                               namespace=ns, job=name,
                               uid=job.metadata.uid)

        # Persist the runtime ID once, before any replica exists (fixes the
        # per-sync in-memory stamping of local.go:79-84).
        if not job.spec.runtime_id and not deleting:
            job.spec.runtime_id = generate_runtime_id()
            try:
                # Keep the returned object: its bumped resourceVersion is
                # what the status CAS fast path below writes against.
                job = self.cluster.tfjobs.update(job)
            except Conflict:
                self.queue.add(key)  # re-read on next pass
                return
            except NotFound:
                return
            # Fall through with the stamped job: the informer will catch up.

        needs_sync = self.expectations.satisfied_expectations(key)

        pods_by_type, services_by_type = self._gather(job)

        # Recovery plane: restart accounting + policy verdicts for every
        # failed replica index (events, metrics, backoff requeue, and —
        # when a gang is about to be replaced — the generation bump that
        # keys the replacement's re-rendezvous).
        job, recovery = self._assess_recovery(
            key, job, pods_by_type, needs_sync=needs_sync and not deleting)

        if needs_sync and not deleting:
            # Serving plane: consult the autoscaler BEFORE planning, so
            # this very sync's plan creates/drains toward the new target.
            job = self._assess_serving(key, job, pods_by_type)
            self._manage(key, job, pods_by_type, services_by_type, recovery)

        # Status rollup runs every sync, whether or not we acted.  The
        # stall tracker rides along: Running pods' heartbeats/steps are
        # checked against the deadlines and surface as Degraded health +
        # stalled progress in the computed status.  The rollup cache skips
        # the whole pass when every input RV is unchanged since the last
        # computation (a hit also proves the stored status already matches,
        # so publication and the status write are skipped with it); jobs
        # whose pods report progress never hit (stall detection is
        # wall-clock-driven and must re-run — see updater/incremental.py).
        fp = RollupCache.fingerprint(job, pods_by_type, recovery)
        new_status = self.rollup_cache.lookup(key, fp)
        if new_status is None:
            new_status = compute_status(job, pods_by_type,
                                        tracker=self.stall_tracker,
                                        recovery=recovery)
            self._publish_progress(key, job, new_status)
            self._publish_gang_state(key, job, pods_by_type)
            self._publish_serving(key, job, pods_by_type, new_status)
            self._observe_goodput(key, job, pods_by_type, new_status)
            if should_update(job.status, new_status):
                self._update_status(job, new_status)
            self.rollup_cache.store(key, fp, new_status)

        # Terminal TPU jobs release their slice once cleanup is planned.
        if (
            self.inventory is not None
            and is_tpu_job(job)
            and new_status.phase.value in ("Succeeded", "Failed")
        ):
            self.inventory.release_gang(gang_name(job))

        # Flight recorder: the first sync that computes this job Failed
        # cuts a postmortem bundle (trace + events + progress + status
        # history + TSDB windows).  Gated on $KCTPU_DEBUG_DIR inside
        # record_flight; once-per-key so a Failed job resyncing forever
        # doesn't re-cut bundles.
        if new_status.phase.value == "Failed":
            with self._flight_lock:
                fresh = key not in self._flight_cut
                self._flight_cut.add(key)
            if fresh:
                self._record_flight(key, job, pods_by_type, new_status,
                                    reason="JobFailed")

    def _publish_progress(self, key: str, job: TFJob, status) -> None:
        """Training-plane outputs of a sync: the per-job progress gauges on
        /metrics, and edge-triggered TrainingStalled/TrainingResumed events
        when the stalled-replica set changes."""
        ns, name = job.metadata.namespace, job.metadata.name
        progress = status.progress
        if progress is None:
            return
        self._g_step.labels(ns, name).set(progress.step)
        self._g_rate.labels(ns, name).set(progress.examples_per_sec)
        self._g_lag.labels(ns, name).set(progress.straggler_lag)
        self._g_stalled.labels(ns, name).set(1.0 if progress.stalled else 0.0)

        now_stalled = frozenset(progress.stalled_replicas)
        with self._stalled_lock:
            before = self._stalled.get(key, frozenset())
            if now_stalled == before:
                return
            self._stalled[key] = now_stalled
        newly = sorted(now_stalled - before)
        recovered = sorted(before - now_stalled)
        if newly:
            by_name = {f"{r.type.value}-{r.index}": r for r in progress.replicas}
            details = []
            for rn in newly:
                r = by_name.get(rn)
                if r is not None and r.last_heartbeat:
                    age = max(0.0, time.time() - r.last_heartbeat)
                    details.append(f"{rn} (step {r.step}, "
                                   f"last heartbeat {age:.1f}s ago)")
                else:
                    details.append(rn)
            self.recorder.event(
                job, TYPE_WARNING, REASON_TRAINING_STALLED,
                f"training stalled on replica {', '.join(details)}")
        if recovered:
            self.recorder.event(
                job, TYPE_NORMAL, REASON_TRAINING_RESUMED,
                f"training resumed on replica {', '.join(recovered)} "
                f"(step {progress.step})")

    def _publish_gang_state(self, key: str, job: TFJob, pods_by_type) -> None:
        """Capacity-plane audit events, edge-triggered on the gang's
        scheduling state as observed through pod status (works in any
        deployment shape — the scheduler publishes queue state as the
        Pending pods' reason, preemption as the Failed pods' reason):

        - ``Normal GangQueued`` with the queue position and why,
        - ``Normal GangAdmitted`` once the gang is on slices and running,
        - ``Warning GangPreempted`` naming the preemptor."""
        from ..api.core import PHASE_FAILED, PHASE_PENDING, PHASE_RUNNING

        if not is_tpu_job(job):
            return
        pods = pods_by_type.get(ReplicaType.TPU, [])
        queue_msg = next(
            (p.status.reason for p in pods
             if p.status.phase == PHASE_PENDING
             and (p.status.reason or "").startswith(
                 POD_REASON_QUEUED_PREFIX)), "")
        preempt_msg = next(
            (p.status.reason for p in pods
             if p.status.phase == PHASE_FAILED
             and (p.status.reason or "").startswith(
                 POD_REASON_PREEMPTED_PREFIX)), "")
        running = sum(1 for p in pods if p.status.phase == PHASE_RUNNING)
        if preempt_msg:
            state = "preempted"
        elif queue_msg:
            state = "queued"
        elif running and running == len(pods) and pods:
            state = "admitted"
        else:
            return  # indeterminate: keep the last edge
        with self._stalled_lock:
            if self._gang_state.get(key) == state:
                return
            self._gang_state[key] = state
        if state == "queued":
            self.recorder.event(job, TYPE_NORMAL, REASON_GANG_QUEUED, queue_msg)
        elif state == "admitted":
            self.recorder.event(
                job, TYPE_NORMAL, REASON_GANG_ADMITTED,
                f"gang {gang_name(job)} admitted: {running} pods running "
                f"on slices {self.inventory.gang_slices(gang_name(job)) if self.inventory else '?'}")
            self._stamp_placement(job)
        else:
            self.recorder.event(job, TYPE_WARNING, REASON_GANG_PREEMPTED,
                                preempt_msg)

    def _stamp_placement(self, job: TFJob) -> None:
        """Persist the admitted gang's placement (slices, DCN domains,
        adjacency score, mesh axis -> scope map) as ONE annotation on the
        TFJob — what `kctpu describe` renders as the Placement section.
        Best-effort: an inventory without topology support just skips."""
        import json

        from ..api.labels import ANNOTATION_PLACEMENT
        from ..api.tfjob import replica_spec_for

        if self.inventory is None:
            return
        placement_of = getattr(self.inventory, "placement_of", None)
        if placement_of is None:
            return
        placement = placement_of(gang_name(job))
        if placement is None:
            return
        spec = replica_spec_for(job, ReplicaType.TPU)
        if spec is not None and spec.tpu is not None and spec.tpu.mesh:
            from ..planner.meshmap import plan_mesh_slices

            try:
                placement["mesh"] = plan_mesh_slices(
                    spec.tpu, len(placement["slices"])).axis_scope()
            except Exception:
                pass  # an undividable degraded width never blocks the stamp
        def apply(m):
            m.annotations[ANNOTATION_PLACEMENT] = json.dumps(
                placement, sort_keys=True)

        try:
            self.cluster.tfjobs.patch_meta(
                job.metadata.namespace, job.metadata.name, apply)
        except NotFound:
            pass

    def _assess_serving(self, key: str, job: TFJob, pods_by_type) -> TFJob:
        """Consult the serving autoscaler; persist a changed target as the
        serving-replicas annotation (ONE metadata patch, exactly like the
        elastic width transitions) so this sync's plan executes it —
        scale-up creates replicas, scale-down drains the highest indices
        gracefully.  Emits the edge-triggered ServingScaledUp/Down events."""
        from ..api.labels import ANNOTATION_SERVING_REPLICAS
        from ..api.tfjob import is_serving_job
        from ..serving.autoscale import serving_width

        if job.spec.autoscale is None or not is_serving_job(job):
            return job
        decision = self.serving_autoscaler.assess(
            key, job, pods_by_type.get(ReplicaType.SERVING, []), time.time())
        if decision.requeue_after_s > 0:
            # A pending scale-down's stabilization window generates no
            # watch events; look again when it elapses.
            self.queue.add_after(key, decision.requeue_after_s + 0.02)
        if decision.target is None:
            return job
        current = serving_width(job)
        if decision.target == current:
            return job
        ns, name = job.metadata.namespace, job.metadata.name

        def apply(m):
            m.annotations[ANNOTATION_SERVING_REPLICAS] = str(decision.target)

        try:
            job = self.cluster.tfjobs.patch_meta(ns, name, apply)
        except NotFound:
            return job
        msg = (f"serving replicas {current} -> {decision.target}: "
               f"{decision.reason}")
        if decision.target > current:
            self._c_serve_scale.labels("up").inc()
            self.recorder.event(job, TYPE_NORMAL, REASON_SERVING_SCALED_UP,
                                msg)
        else:
            self._c_serve_scale.labels("down").inc()
            self.recorder.event(job, TYPE_NORMAL, REASON_SERVING_SCALED_DOWN,
                                msg)
        return job

    def _publish_serving(self, key: str, job: TFJob, pods_by_type,
                         status) -> None:
        """Serving-plane gauges from this sync's rollup: job-level
        qps/TTFT/replicas plus one queue-depth + occupancy series per
        replica index.  Indices that left (scale-down, job shrink) have
        their series REMOVED — Gauge.remove frees the metric series
        budget, so an autoscaling job cannot strand one dead series per
        replica index it ever ran."""
        sv = getattr(status, "serving", None)
        if sv is None:
            return
        ns, name = job.metadata.namespace, job.metadata.name
        self._g_serve_qps.labels(ns, name).set(sv.qps)
        self._g_serve_ttft.labels(ns, name).set(sv.ttft_ms)
        self._g_serve_ttft_p99.labels(ns, name).set(sv.ttft_p99_ms)
        self._g_serve_replicas.labels(ns, name).set(sv.replicas)
        self._g_serve_ready.labels(ns, name).set(sv.ready)
        live = set()
        for p in pods_by_type.get(ReplicaType.SERVING, []):
            pr = p.status.progress
            idx = pod_index(p)
            if pr is None or idx is None or not pr.slots_total:
                continue
            live.add(str(idx))
            self._g_serve_queue.labels(ns, name, str(idx)).set(
                pr.queue_depth)
            self._g_serve_occ.labels(ns, name, str(idx)).set(
                pr.slots_used / pr.slots_total)
        with self._stalled_lock:
            before = self._serving_series.get(key, frozenset())
            self._serving_series[key] = frozenset(live)
        for idx in before - live:
            self._g_serve_queue.remove(ns, name, idx)
            self._g_serve_occ.remove(ns, name, idx)

    def _observe_goodput(self, key: str, job: TFJob, pods_by_type,
                         status) -> None:
        """Fold this sync's observed pods into the goodput ledger
        (obs/goodput.py) and surface the rollup.

        Runs on every rollup-cache miss — the only syncs where a bucket
        can have changed, since every bucket input (pod phase, beat,
        stall verdict) either bumps a pod RV or defeats the cache
        (progress-bearing jobs never hit).  The ledger's open intervals
        accrue wall-clock time regardless of sync cadence.  The
        quantized ``status.goodput`` checkpoint is attached at most once
        per ``goodput_status_interval_s`` (plus ONE terminal-edge
        flush); between attachments the previously persisted value is
        carried so ``should_update`` sees no goodput-only churn."""
        if self.goodput_tracker is None:
            # Ledger disabled (bench.py --scale overhead comparison).
            status.goodput = job.status.goodput
            return
        ns, name = job.metadata.namespace, job.metadata.name
        now = time.time()
        if (job.status.goodput is not None
                and not self.goodput_tracker.has_job(ns, name)):
            # Controller failover: adopt the bucket totals the previous
            # leader persisted, then account forward from here.
            self.goodput_tracker.bootstrap(
                ns, name, dict(job.status.goodput.buckets))
        stalled = (set(status.progress.stalled_replicas)
                   if status.progress is not None else set())
        observations = []
        for typ, pods in (pods_by_type or {}).items():
            for p in pods:
                pr = p.status.progress
                idx = pod_index(p)
                rname = f"{typ.value}-{idx}" if idx is not None else ""
                observations.append(PodObservation(
                    name=p.metadata.name,
                    pod_phase=p.status.phase,
                    reason=p.status.reason or "",
                    start_mode=p.metadata.annotations.get(
                        ANNOTATION_START_MODE, ""),
                    beat_phase=pr.phase if pr is not None else None,
                    compile_source=pr.compile_source if pr is not None else "",
                    stalled=rname in stalled,
                ))
        self.goodput_tracker.observe(ns, name, observations, now)
        self.goodput_tracker.set_tenant(ns, name, tenant_of(job))
        terminal = status.phase in (TFJobPhase.SUCCEEDED, TFJobPhase.FAILED)
        with self._stalled_lock:
            last = self._goodput_pub.get(key, 0.0)
            # The terminal edge flushes ONCE (sentinel inf): a finished
            # job keeps syncing while its pods drain, and re-attaching a
            # still-growing rollup each time would churn status forever.
            due = ((terminal and last != float("inf"))
                   or now - last >= self.goodput_status_interval_s)
            if due:
                self._goodput_pub[key] = float("inf") if terminal else now
        if not due:
            # Off the publish edge the ledger only accrues — the rollup
            # walk and metric push wait for the quantized interval (this
            # keeps the per-sync ledger cost flat; bench --goodput gates
            # the --scale overhead on it).
            status.goodput = job.status.goodput
            return
        summary = self.goodput_tracker.summary(ns, name, now)
        self.goodput_tracker.publish(ns, name, now)
        if summary is not None and summary.wall_s >= 1.0:
            status.goodput = JobGoodput(
                goodput_s=int(summary.goodput_s),
                occupied_s=int(summary.occupied_s),
                wall_s=int(summary.wall_s),
                ratio=round(summary.ratio, 2),
                buckets={b: int(v) for b, v in summary.buckets.items()
                         if int(v) > 0})
        else:
            status.goodput = job.status.goodput

    def _record_flight(self, key: str, job: TFJob, pods_by_type,
                       status, reason: str) -> Optional[str]:
        """Capture the postmortem bundle for ``job`` (obs/flight.py).
        Returns the bundle path, or None when flight recording is off."""
        from ..obs import flight

        ns, name = job.metadata.namespace, job.metadata.name
        ctx = trace_context_for(job)
        progress = {}
        for typ, pods in (pods_by_type or {}).items():
            for p in pods:
                if p.status.progress is not None:
                    progress[p.metadata.name] = serde.to_dict(
                        p.status.progress)
        path = flight.record_flight(
            ns, name, reason=reason,
            trace_id=ctx.trace_id if ctx else "",
            events=[{"type": e.type, "reason": e.reason,
                     "message": e.message, "count": e.count,
                     "timestamp": e.timestamp,
                     "firstTimestamp": e.first_timestamp}
                    for e in self.recorder.events_for(ns, name)],
            progress=progress,
            status_history=job_lifecycle().history(job.metadata.uid),
            status=serde.to_dict(status),
            tsdb=self._tsdb,
            goodput=(self.goodput_tracker.snapshot(ns, name, time.time())
                     if self.goodput_tracker is not None else None))
        if path:
            logger.info("flight recorder: wrote %s for %s (%s)",
                        path, key, reason)
        return path

    def flight_dump(self, namespace: str, name: str,
                    reason: str = "OnDemand") -> Optional[str]:
        """On-demand postmortem capture (``kctpu debug dump JOB``) — same
        bundle the Failed edge cuts, for a live job."""
        job = self.tfjob_informer.get(namespace, name)
        if job is None:
            return None
        pods_by_type, _ = self._gather(job)
        return self._record_flight(key_of(job.metadata), job, pods_by_type,
                                   job.status, reason=reason)

    def _drop_serving_series(self, key: str, job: Optional[TFJob] = None) -> None:
        """Serving gauge series die with the job.  Called from the delete
        handler, the finalizer, AND the final ``job is None`` sync: the
        last call is ordered after any in-flight sync's publish (per-key
        serialization), so a publish racing the delete handler cannot
        strand a dead series."""
        ns, name = split_key(key)
        with self._stalled_lock:
            indices = self._serving_series.pop(key, frozenset())
        for idx in indices:
            self._g_serve_queue.remove(ns, name, idx)
            self._g_serve_occ.remove(ns, name, idx)
        for g in (self._g_serve_qps, self._g_serve_ttft,
                  self._g_serve_ttft_p99,
                  self._g_serve_replicas, self._g_serve_ready):
            g.remove(ns, name)
        self.serving_autoscaler.forget_job(key)

    def _drop_goodput(self, key: str) -> None:
        """Goodput series + ledger state die with the job (same triple
        call-site discipline as _drop_serving_series)."""
        if self.goodput_tracker is None:
            return
        ns, name = split_key(key)
        self.goodput_tracker.drop(ns, name)
        with self._stalled_lock:
            self._goodput_pub.pop(key, None)

    def _drop_progress_series(self, key: str, job: TFJob) -> None:
        """Per-job gauge series + stall bookkeeping die with the job."""
        from .helper import OWNER_UID_INDEX

        ns, name = job.metadata.namespace, job.metadata.name
        for g in (self._g_step, self._g_rate, self._g_lag, self._g_stalled):
            g.remove(ns, name)
        with self._stalled_lock:
            self._stalled.pop(key, None)
            self._gang_state.pop(key, None)
        if job.metadata.uid:
            for p in self.pod_informer.by_index(OWNER_UID_INDEX,
                                                job.metadata.uid):
                self.stall_tracker.forget(
                    f"{p.metadata.namespace}/{p.metadata.name}")

    def _finalize_job(self, key: str, job: TFJob) -> None:
        """Cleanup under our finalizer: release the TPU gang, delete child
        pods/services explicitly, then drop the finalizer — the API server
        finalizes (removes) the job once the list empties."""
        ns, name = job.metadata.namespace, job.metadata.name
        self._drop_progress_series(key, job)
        self._drop_serving_series(key, job)
        self._drop_goodput(key)
        if self.inventory is not None and is_tpu_job(job):
            self.inventory.release_gang(gang_name(job))
        if job.spec.runtime_id:  # no children can exist before stamping
            selector = job_selector(name, job.spec.runtime_id)
            for pod in self.cluster.pods.list(ns, selector=selector):
                try:
                    self.cluster.pods.delete(ns, pod.metadata.name)
                    self.metrics.inc_deletes()
                except NotFound:
                    pass
            for svc in self.cluster.services.list(ns, selector=selector):
                try:
                    self.cluster.services.delete(ns, svc.metadata.name)
                    self.metrics.inc_deletes()
                except NotFound:
                    pass
        if FINALIZER in job.metadata.finalizers:
            def drop(m):
                if FINALIZER in m.finalizers:
                    m.finalizers.remove(FINALIZER)

            try:
                self.cluster.tfjobs.patch_meta(ns, name, drop)
            except NotFound:
                pass
        self.expectations.delete_expectations(key)
        self.restart_tracker.forget_job(key)
        self.elastic_engine.forget_job(key, job)
        self.rollup_cache.forget(key)
        if job.metadata.uid:
            job_lifecycle().forget(job.metadata.uid)
        with self._flight_lock:
            self._flight_cut.discard(key)

    def _gather(self, job: TFJob):
        """Claim pods/services once at job scope, then partition by replica
        type (ref: controller.go:299-320 — but see api.labels.job_selector
        for why we claim once instead of per type)."""
        with trace.span("sync/gather", job=job.metadata.name):
            return self._gather_inner(job)

    def _gather_inner(self, job: TFJob):
        selector = job_selector(job.metadata.name, job.spec.runtime_id)
        pods = self.helper.get_pods_for_tfjob(job, selector)
        services = self.helper.get_services_for_tfjob(job, selector)
        pods_by_type: Dict[ReplicaType, List[Pod]] = {}
        services_by_type: Dict[ReplicaType, List[Service]] = {}
        for spec in job.spec.tf_replica_specs:
            typ = spec.tf_replica_type
            pods_by_type[typ] = [
                p for p in pods if p.metadata.labels.get(LABEL_JOB_TYPE) == typ.value
            ]
            services_by_type[typ] = [
                s for s in services if s.metadata.labels.get(LABEL_JOB_TYPE) == typ.value
            ]
        return pods_by_type, services_by_type

    def _assess_recovery(self, key: str, job: TFJob, pods_by_type,
                         needs_sync: bool):
        """Run the restart policy engine over this sync's pod view; emit
        the recovery-plane events, schedule the backoff requeue, and bump
        the job's gang generation when a gang replacement will execute this
        sync.  Returns (possibly generation-patched job, assessment)."""
        recovery = self.restart_tracker.assess(key, job, pods_by_type,
                                               time.time())
        for nf in recovery.new_failures:
            d = nf.decision
            if d.action == ACTION_EXHAUSTED:
                continue  # the newly_exhausted edge below tells the story
            if d.action not in (ACTION_REPLACE, ACTION_BACKOFF):
                continue  # restartPolicy Never: terminal, no restart event
            delay = (f" after {d.delay_s:.2g}s backoff" if d.delay_s > 0
                     else "")
            why = f": {nf.reason}" if nf.reason else ""
            self.recorder.event(
                job, TYPE_NORMAL, REASON_REPLICA_RESTARTED,
                f"replica {nf.type.value}-{nf.index} restart #{d.count}"
                f"{delay} (pod {nf.pod_name}{why})",
                dedup_key=f"{nf.type.value}-{nf.index}")
        for typ, idx, d in recovery.newly_exhausted:
            self.recorder.event(
                job, TYPE_WARNING, REASON_BACKOFF_LIMIT_EXCEEDED,
                f"replica {typ.value}-{idx} failed {d.count} times "
                f"(streak {d.streak} > backoffLimit "
                f"{job.spec.backoff_limit}); giving up — job failed",
                dedup_key=f"{typ.value}-{idx}")
        if recovery.requeue_after_s > 0:
            # A Failed pod emits no further watch events; without this the
            # backoff window would only be noticed by a resync.
            self.queue.add_after(key, recovery.requeue_after_s + 0.02)
        if needs_sync:
            # Elastic plane first: an applied width transition IS this
            # gang's generation bump (degrade/harvest/re-expand); only
            # non-elastic paths fall through to the whole-gang bump.
            job, applied = self._assess_elastic(key, job, pods_by_type,
                                                recovery)
            if not applied:
                job = self._maybe_bump_gang_generation(key, job,
                                                       pods_by_type,
                                                       recovery)
        return job, recovery

    def _assess_elastic(self, key: str, job: TFJob, pods_by_type,
                        recovery):
        """Consult the width transition engine; apply a proposed
        transition as ONE metadata patch — gang-generation + 1 and the
        gang-width annotation — so this very sync's plan replaces the
        stale generation at the new width.  Emits the edge-triggered
        ``Warning GangDegraded`` / ``Normal GangRestored`` events (each
        transition is an edge by construction: the bump retires the
        failed generation the engine keyed on).  Returns (possibly
        patched job, transition-applied?)."""
        from ..api.labels import (
            ANNOTATION_GANG_GENERATION,
            ANNOTATION_GANG_WIDTH,
        )
        from ..elastic import KIND_EXPAND

        a = self.elastic_engine.assess(
            key, job, pods_by_type, recovery, time.time(),
            inventory=self.inventory)
        if a is None:
            return job, False
        if a.requeue_after_s > 0:
            # Warm-up expiry and freed capacity emit no watch events on
            # the job; the engine names when it next needs to look.
            self.queue.add_after(key, a.requeue_after_s + 0.02)
        tr = a.transition
        if tr is None:
            return job, False
        ns, name = job.metadata.namespace, job.metadata.name
        cur = int(job.metadata.annotations.get(ANNOTATION_GANG_GENERATION,
                                               "0") or "0")

        def apply(m):
            m.annotations[ANNOTATION_GANG_GENERATION] = str(cur + 1)
            m.annotations[ANNOTATION_GANG_WIDTH] = str(tr.to_width)

        try:
            job = self.cluster.tfjobs.patch_meta(ns, name, apply)
        except NotFound:
            return job, False
        if tr.kind == KIND_EXPAND:
            if tr.complete:
                self.recorder.event(
                    job, TYPE_NORMAL, REASON_GANG_RESTORED,
                    f"gang re-expanded to full width {tr.to_width} "
                    f"(from {tr.from_width}); resuming from the degraded "
                    f"run's checkpoint")
        else:
            why = f" ({tr.reason})" if tr.reason else ""
            self.recorder.event(
                job, TYPE_WARNING, REASON_GANG_DEGRADED,
                f"gang width {tr.from_width} -> {tr.to_width} "
                f"[{tr.kind}]{why}; survivors re-shard from the latest "
                f"checkpoint and keep training while the replacement "
                f"warms")
        return job, True

    def _maybe_bump_gang_generation(self, key: str, job: TFJob,
                                    pods_by_type, recovery) -> TFJob:
        """A gang about to be replaced gets a fresh generation, persisted
        as a job annotation BEFORE the replacement pods are materialized:
        the planner stamps it into every member (annotation + env), keying
        the new gang's rendezvous namespace — readiness drops and
        fake-DNS coordinator ports — away from the dead generation's."""
        from ..api.labels import ANNOTATION_GANG_GENERATION
        from ..planner.plan import is_gang_spec

        will_replace = False
        for spec in job.spec.tf_replica_specs:
            if not is_gang_spec(spec):
                continue
            typ = spec.tf_replica_type
            restart = (spec.template.spec.restart_policy
                       if spec.template else "OnFailure")
            if restart not in ("OnFailure", "Always"):
                continue
            verdicts = [d.action for (t, _), d in recovery.decisions.items()
                        if t == typ]
            if verdicts and all(v == ACTION_REPLACE for v in verdicts):
                will_replace = True
        if not will_replace:
            return job

        ns, name = job.metadata.namespace, job.metadata.name
        cur = int(job.metadata.annotations.get(ANNOTATION_GANG_GENERATION,
                                               "0") or "0")

        def bump(m):
            m.annotations[ANNOTATION_GANG_GENERATION] = str(cur + 1)

        try:
            return self.cluster.tfjobs.patch_meta(ns, name, bump)
        except NotFound:
            return job

    def _manage(self, key, job, pods_by_type, services_by_type,
                recovery=None) -> None:
        """Execute the plan (ref: manageTFJob at controller.go:359-445)."""
        with trace.span("sync/manage", key=key) as sp:
            self._manage_inner(key, job, pods_by_type, services_by_type, sp,
                               recovery)

    def _manage_inner(self, key, job, pods_by_type, services_by_type, sp,
                      recovery=None) -> None:
        """Execute the plan through slow-start batches (client-go's
        ``slowStartBatch``; see slowstart.py).  Three ordered phases keep
        the serial invariants — deletes land before the creates that reuse
        their indices/names, services before the pods whose cluster specs
        name them — while each phase fans out on the bounded manage pool.

        Error semantics (the write-side contract):

        - every event in a launched batch is attempted; a failed event
          lowers its own expectation (its watch event will never arrive,
          ref: controller.go:381-383) and the rest of the batch drains;
        - the first failing batch stops NEW batches and later phases;
          skipped events' expectations are lowered here so the next sync
          re-plans exactly the missing children instead of waiting out the
          expectations TTL;
        - all errors are aggregated into one ManageError so the sync
          requeues with backoff, instead of the historical abort-on-first
          that silently dropped the remaining replicas' events."""
        plan = plan_job(job, pods_by_type, services_by_type, recovery)
        sp.args["creations"] = plan.creations
        sp.args["deletions"] = plan.deletions
        if plan.empty:
            return
        self.expectations.expect(key, plan.creations, plan.deletions)

        adds = (Action.ADD_POD, Action.ADD_SERVICE)
        phases = (
            [ev for ev in plan.events if ev.action not in adds],     # deletes
            [ev for ev in plan.events if ev.action == Action.ADD_SERVICE],
            [ev for ev in plan.events if ev.action == Action.ADD_POD],
        )
        executor = self._manage_executor()

        def batch_cm(n: int):
            self._h_batch.observe(n)
            return trace.span("sync/manage/batch", key=key, n=n)

        errors: List[BaseException] = []
        attempted = skipped_adds = skipped_dels = 0
        for evs in phases:
            if errors:
                # A failed earlier phase: creates that would collide with
                # an undeleted name, or follow a failed sibling, are not
                # launched — but their expectations must not dangle.
                skipped_adds += sum(1 for ev in evs if ev.action in adds)
                skipped_dels += sum(1 for ev in evs if ev.action not in adds)
                continue
            done, errs, skipped = slow_start_batch(
                evs, lambda ev: self._execute_event(key, job, ev),
                executor=executor, batch_cm=batch_cm)
            attempted += done + len(errs)
            errors.extend(errs)
            skipped_adds += sum(1 for ev in skipped if ev.action in adds)
            skipped_dels += sum(1 for ev in skipped if ev.action not in adds)

        if errors:
            if skipped_adds:
                self.expectations.lower_expectations(
                    key, add_delta=skipped_adds)
            if skipped_dels:
                self.expectations.lower_expectations(
                    key, del_delta=skipped_dels)
            raise ManageError(errors, attempted=attempted,
                              skipped=skipped_adds + skipped_dels)

    def _execute_event(self, key: str, job: TFJob, ev) -> None:
        """One plan event -> one cluster write.  Runs on manage-pool threads
        on the parallel path: everything it touches is thread-safe (Helper
        deep-copies templates, EventRecorder and ReconcileMetrics lock,
        ControllerExpectations locks, the job object is this sync's private
        deep copy and is only read)."""
        spec = replica_spec_for(job, ev.replica_type)
        try:
            if ev.action == Action.ADD_SERVICE:
                self.helper.create_service(job, make_service(job, spec, ev.index))
                self.metrics.inc_creates()
            elif ev.action == Action.ADD_POD:
                self.helper.create_pod(job, make_pod(job, spec, ev.index))
                self.metrics.inc_creates()
            elif ev.action == Action.DELETE_POD:
                if self.helper.delete_pod(job, job.metadata.namespace, ev.name):
                    self.metrics.inc_deletes()
                else:
                    # Already gone: no DELETED event will arrive.
                    self.expectations.lower_expectations(key, del_delta=1)
            elif ev.action == Action.DELETE_SERVICE:
                if self.helper.delete_service(job, job.metadata.namespace, ev.name):
                    self.metrics.inc_deletes()
                else:
                    self.expectations.lower_expectations(key, del_delta=1)
            elif ev.action == Action.DRAIN_POD:
                self._drain_pod(job, ev)
        except Exception:
            # The watch event will never arrive; decrement so the TTL
            # does not block the next sync (ref: controller.go:381-383).
            # Drains hold no expectation (their MODIFIED event is not
            # awaited), so there is nothing to lower.
            if ev.action in (Action.ADD_POD, Action.ADD_SERVICE):
                self.expectations.lower_expectations(key, add_delta=1)
            elif ev.action != Action.DRAIN_POD:
                self.expectations.lower_expectations(key, del_delta=1)
            raise

    def _drain_pod(self, job: TFJob, ev) -> None:
        """Serving graceful drain: stamp the pod's drain annotation (the
        kubelet SIGTERMs executed replicas / completes simulated ones once
        their beats show an empty queue) and record the audit event.  The
        pod's MODIFIED watch event re-enqueues the job, so no expectations
        entry is needed."""
        from ..api.labels import ANNOTATION_DRAIN

        def mark(m):
            m.annotations[ANNOTATION_DRAIN] = ev.reason or "drain"

        try:
            self.cluster.pods.patch_meta(job.metadata.namespace, ev.name,
                                         mark)
        except NotFound:
            return  # already gone: nothing to drain
        self.recorder.event(
            job, TYPE_NORMAL, REASON_SERVING_DRAINING,
            f"draining serving replica {ev.replica_type.value}-{ev.index} "
            f"(pod {ev.name}, {ev.reason or 'drain'}): stop intake, "
            f"finish in-flight, exit",
            dedup_key=ev.name)

    def _manage_executor(self) -> Optional[ThreadPoolExecutor]:
        """The shared bounded manage pool; None selects the serial path."""
        if self.manage_workers <= 1:
            return None
        if self._manage_pool is None:
            with self._manage_pool_lock:
                if self._manage_pool is None and not self._stop.is_set():
                    self._manage_pool = ThreadPoolExecutor(
                        max_workers=self.manage_workers,
                        thread_name_prefix="manage-worker")
        return self._manage_pool

    def _update_status(self, job: TFJob, new_status) -> None:
        """Status write with conflict retry (the reference's bare Update with
        no retry is its known weakness, controller.go:643-649)."""
        with trace.span("sync/update_status", job=job.metadata.name):
            self._update_status_inner(job, new_status)

    def _update_status_inner(self, job: TFJob, new_status) -> None:
        # Fast path: CAS with the resourceVersion already in hand.  The sync
        # just read (or wrote) this job, so in steady state the RV is
        # current and the write lands first try — no GET round-trip.  Only
        # a genuinely concurrent writer sends us to the GET+retry loop.
        if job.metadata.resource_version:
            job.status = new_status
            try:
                self.cluster.tfjobs.update_status(job)
                self.metrics.inc_status_updates()
                return
            except NotFound:
                return
            except Conflict:
                pass
        for attempt in range(MAX_STATUS_RETRIES):
            try:
                fresh = self.cluster.tfjobs.get(job.metadata.namespace, job.metadata.name)
            except NotFound:
                return
            fresh.status = new_status
            try:
                self.cluster.tfjobs.update_status(fresh)
                self.metrics.inc_status_updates()
                return
            except Conflict:
                continue
        logger.warning("giving up status update for %s after %d conflicts",
                       key_of(job.metadata), MAX_STATUS_RETRIES)
