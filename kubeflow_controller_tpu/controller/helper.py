"""Helper: bridges planner output to cluster writes and cached reads.

Semantic re-implementation of ``HelperInterface`` (ref: pkg/controller/
helper.go:45-51):

- ``create_pod`` / ``create_service``: stamp the controller ownerRef
  (controller=true, blockOwnerDeletion=true, ref: util.go:43-54), validate it
  (ref: control/util.go:25-42), refuse empty labels (ref: control/
  service.go:67-69), create through the client, and emit
  SuccessfulCreate/FailedCreate events (ref: control/service.go:72-84);
- ``get_pods_for_tfjob`` / ``get_services_for_tfjob``: list by the 4-label
  selector (ref: helper.go:118-125), then adopt/release through the
  :class:`RefManager` with a live-read ``can_adopt`` gate re-checking the
  job's UID (ref: helper.go:137-148).
"""

from __future__ import annotations

from typing import Dict, List

from ..api.core import Pod, Service
from ..api.meta import set_controller_ref, validate_controller_ref, get_controller_of
from ..api.tfjob import API_VERSION, KIND, TFJob
from ..cluster.client import Cluster
from ..cluster.store import NotFound
from ..utils import serde
from .events import (
    EventRecorder,
    REASON_FAILED_CREATE,
    REASON_SUCCESSFUL_CREATE,
    REASON_SUCCESSFUL_DELETE,
    TYPE_NORMAL,
    TYPE_WARNING,
)
from .refmanager import RefManager


class Helper:
    def __init__(self, cluster: Cluster, recorder: EventRecorder):
        self.cluster = cluster
        self.recorder = recorder

    # -- writes --------------------------------------------------------------

    def create_pod(self, job: TFJob, pod: Pod) -> Pod:
        pod = serde.deep_copy(pod)
        pod.metadata.namespace = job.metadata.namespace
        if not pod.metadata.labels:
            raise ValueError("pod template has no labels; refusing to create")
        set_controller_ref(pod.metadata, job.metadata, API_VERSION, KIND)
        validate_controller_ref(get_controller_of(pod.metadata))
        try:
            created = self.cluster.pods.create(pod)
        except Exception as e:
            self.recorder.event(job, TYPE_WARNING, REASON_FAILED_CREATE,
                                f"Error creating pod: {e}")
            raise
        self.recorder.event(job, TYPE_NORMAL, REASON_SUCCESSFUL_CREATE,
                            f"Created pod: {created.metadata.name}")
        return created

    def create_service(self, job: TFJob, service: Service) -> Service:
        service = serde.deep_copy(service)
        service.metadata.namespace = job.metadata.namespace
        if not service.metadata.labels:
            raise ValueError("service template has no labels; refusing to create")
        set_controller_ref(service.metadata, job.metadata, API_VERSION, KIND)
        validate_controller_ref(get_controller_of(service.metadata))
        try:
            created = self.cluster.services.create(service)
        except Exception as e:
            self.recorder.event(job, TYPE_WARNING, REASON_FAILED_CREATE,
                                f"Error creating service: {e}")
            raise
        self.recorder.event(job, TYPE_NORMAL, REASON_SUCCESSFUL_CREATE,
                            f"Created service: {created.metadata.name}")
        return created

    def patch_service(self, namespace: str, name: str, body: dict) -> Service:
        """Arbitrary service mutation as a server-side merge patch — the
        PatchService surface the reference exposes on its service control
        (ref: pkg/controller/control/service.go:50-53), e.g.
        ``patch_service(ns, n, {"spec": {"selector": {...}}})``."""
        return self.cluster.services.patch(namespace, name, body)

    def delete_pod(self, job: TFJob, namespace: str, name: str) -> bool:
        """Index-preserving replacement and recycle need real deletes —
        the capability the reference stubbed (controller.go:522-524).
        Returns False when the pod was already gone (no DELETED watch event
        will arrive; the caller must lower its deletion expectation)."""
        try:
            self.cluster.pods.delete(namespace, name)
        except NotFound:
            return False
        self.recorder.event(job, TYPE_NORMAL, REASON_SUCCESSFUL_DELETE,
                            f"Deleted pod: {name}")
        return True

    def delete_service(self, job: TFJob, namespace: str, name: str) -> bool:
        try:
            self.cluster.services.delete(namespace, name)
        except NotFound:
            return False
        self.recorder.event(job, TYPE_NORMAL, REASON_SUCCESSFUL_DELETE,
                            f"Deleted service: {name}")
        return True

    # -- reads + adoption ----------------------------------------------------

    def _can_adopt_fn(self, job: TFJob):
        """Live (uncached) re-read of the job, vetoing adoption if the cached
        UID is stale or the job is being deleted (ref: helper.go:137-146)."""

        def can_adopt() -> None:
            fresh = self.cluster.tfjobs.get(job.metadata.namespace, job.metadata.name)
            if fresh.metadata.uid != job.metadata.uid:
                raise RuntimeError(
                    f"original TFJob {job.metadata.name} is gone: got uid "
                    f"{fresh.metadata.uid}, wanted {job.metadata.uid}"
                )
            if fresh.metadata.deletion_timestamp is not None:
                raise RuntimeError(f"TFJob {job.metadata.name} is being deleted")

        return can_adopt

    def get_pods_for_tfjob(self, job: TFJob, selector: Dict[str, str]) -> List[Pod]:
        # List everything in the namespace, then claim — the reference does
        # the same ("It is a hack", helper.go:131-136) so adoption can see
        # orphans whose labels do not match the selector yet.
        pods = self.cluster.pods.list(job.metadata.namespace)
        mgr = RefManager(
            self.cluster.pods, job.metadata, KIND, API_VERSION,
            selector, self._can_adopt_fn(job),
        )
        return mgr.claim(pods)

    def get_services_for_tfjob(self, job: TFJob, selector: Dict[str, str]) -> List[Service]:
        services = self.cluster.services.list(job.metadata.namespace)
        mgr = RefManager(
            self.cluster.services, job.metadata, KIND, API_VERSION,
            selector, self._can_adopt_fn(job),
        )
        return mgr.claim(services)
