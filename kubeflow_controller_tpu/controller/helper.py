"""Helper: bridges planner output to cluster writes and cached reads.

Semantic re-implementation of ``HelperInterface`` (ref: pkg/controller/
helper.go:45-51):

- ``create_pod`` / ``create_service``: stamp the controller ownerRef
  (controller=true, blockOwnerDeletion=true, ref: util.go:43-54), validate it
  (ref: control/util.go:25-42), refuse empty labels (ref: control/
  service.go:67-69), create through the client, and emit
  SuccessfulCreate/FailedCreate events (ref: control/service.go:72-84);
- ``get_pods_for_tfjob`` / ``get_services_for_tfjob``: gather candidates,
  then adopt/release through the :class:`RefManager` with a live-read
  ``can_adopt`` gate re-checking the job's UID (ref: helper.go:137-148).

Gathering reads the **informer indices** when the controller plumbed its
pod/service informers in (owner-UID index ∪ job-selector index — the
client-go pattern of serving steady-state syncs from the local cache), so a
sync of one job is O(own children), not O(namespace).  The reference instead
full-LISTs the namespace every sync so adoption can see orphans
("It is a hack", helper.go:131-136); that live LIST is kept, but only as the
fallback for the one transition that must run against fresh state: when the
selector index shows an unowned candidate that may need adoption.  Release
(owned but selector-mismatched) stays on the cached path — the server-side
``patch_meta`` it issues is safe against stale candidates by construction.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

from ..api.core import Pod, Service
from ..api.meta import set_controller_ref, validate_controller_ref, get_controller_of
from ..api.labels import job_selector_index_keys
from ..api.tfjob import API_VERSION, KIND, TFJob
from ..cluster.client import Cluster
from ..cluster.store import NotFound
from ..obs.metrics import REGISTRY
from ..utils import serde
from .events import (
    EventRecorder,
    REASON_FAILED_CREATE,
    REASON_SUCCESSFUL_CREATE,
    REASON_SUCCESSFUL_DELETE,
    TYPE_NORMAL,
    TYPE_WARNING,
)
from .refmanager import RefManager, has_adoption_candidates

# Index names registered on the pod/service informers (Controller.__init__).
OWNER_UID_INDEX = "owner_uid"
JOB_SELECTOR_INDEX = "job_selector"


def owner_uid_index_keys(obj) -> List[str]:
    """Indexer fn: the UID of the controlling owner, if any."""
    ref = get_controller_of(obj.metadata)
    return [ref.uid] if ref is not None and ref.uid else []


def register_gather_indexers(informer) -> None:
    """Install the two indices the indexed gather path reads."""
    informer.add_indexer(OWNER_UID_INDEX, owner_uid_index_keys)
    informer.add_indexer(JOB_SELECTOR_INDEX,
                         lambda o: job_selector_index_keys(o.metadata.labels))


class Helper:
    def __init__(self, cluster: Cluster, recorder: EventRecorder,
                 pod_informer=None, service_informer=None, metrics=None):
        self.cluster = cluster
        self.recorder = recorder
        # Optional indexed caches (plumbed by the Controller); without them
        # every gather degrades to the reference's live full-LIST behavior.
        self.pod_informer = pod_informer
        self.service_informer = service_informer
        self.metrics = metrics
        # Per-create API latency: the quantity the wide-job bench gates on
        # (a serial manage pays 2×replicas of these back-to-back; the
        # slow-start batches overlap them).  One histogram for pods and
        # services — the label split wasn't worth the cardinality.
        self._h_create_latency = REGISTRY.histogram(
            "kctpu_create_latency_seconds",
            "Child create API call latency (pods and services)")

    def _observe_create(self, t0: float) -> None:
        dur = time.monotonic() - t0
        self._h_create_latency.observe(dur)
        if self.metrics is not None:
            self.metrics.record_create_latency(dur)

    # -- writes --------------------------------------------------------------

    def create_pod(self, job: TFJob, pod: Pod) -> Pod:
        pod = serde.deep_copy(pod)
        pod.metadata.namespace = job.metadata.namespace
        if not pod.metadata.labels:
            raise ValueError("pod template has no labels; refusing to create")
        set_controller_ref(pod.metadata, job.metadata, API_VERSION, KIND)
        validate_controller_ref(get_controller_of(pod.metadata))
        t0 = time.monotonic()
        try:
            created = self.cluster.pods.create(pod)
            self._observe_create(t0)
        except Exception as e:
            self.recorder.event(job, TYPE_WARNING, REASON_FAILED_CREATE,
                                f"Error creating pod: {e}")
            raise
        self.recorder.event(job, TYPE_NORMAL, REASON_SUCCESSFUL_CREATE,
                            f"Created pod: {created.metadata.name}")
        return created

    def create_service(self, job: TFJob, service: Service) -> Service:
        service = serde.deep_copy(service)
        service.metadata.namespace = job.metadata.namespace
        if not service.metadata.labels:
            raise ValueError("service template has no labels; refusing to create")
        set_controller_ref(service.metadata, job.metadata, API_VERSION, KIND)
        validate_controller_ref(get_controller_of(service.metadata))
        t0 = time.monotonic()
        try:
            created = self.cluster.services.create(service)
            self._observe_create(t0)
        except Exception as e:
            self.recorder.event(job, TYPE_WARNING, REASON_FAILED_CREATE,
                                f"Error creating service: {e}")
            raise
        self.recorder.event(job, TYPE_NORMAL, REASON_SUCCESSFUL_CREATE,
                            f"Created service: {created.metadata.name}")
        return created

    def patch_service(self, namespace: str, name: str, body: dict) -> Service:
        """Arbitrary service mutation as a server-side merge patch — the
        PatchService surface the reference exposes on its service control
        (ref: pkg/controller/control/service.go:50-53), e.g.
        ``patch_service(ns, n, {"spec": {"selector": {...}}})``."""
        return self.cluster.services.patch(namespace, name, body)

    def delete_pod(self, job: TFJob, namespace: str, name: str) -> bool:
        """Index-preserving replacement and recycle need real deletes —
        the capability the reference stubbed (controller.go:522-524).
        Returns False when the pod was already gone (no DELETED watch event
        will arrive; the caller must lower its deletion expectation)."""
        try:
            self.cluster.pods.delete(namespace, name)
        except NotFound:
            return False
        self.recorder.event(job, TYPE_NORMAL, REASON_SUCCESSFUL_DELETE,
                            f"Deleted pod: {name}")
        return True

    def delete_service(self, job: TFJob, namespace: str, name: str) -> bool:
        try:
            self.cluster.services.delete(namespace, name)
        except NotFound:
            return False
        self.recorder.event(job, TYPE_NORMAL, REASON_SUCCESSFUL_DELETE,
                            f"Deleted service: {name}")
        return True

    # -- reads + adoption ----------------------------------------------------

    def _can_adopt_fn(self, job: TFJob):
        """Live (uncached) re-read of the job, vetoing adoption if the cached
        UID is stale or the job is being deleted (ref: helper.go:137-146)."""

        def can_adopt() -> None:
            fresh = self.cluster.tfjobs.get(job.metadata.namespace, job.metadata.name)
            if fresh.metadata.uid != job.metadata.uid:
                raise RuntimeError(
                    f"original TFJob {job.metadata.name} is gone: got uid "
                    f"{fresh.metadata.uid}, wanted {job.metadata.uid}"
                )
            if fresh.metadata.deletion_timestamp is not None:
                raise RuntimeError(f"TFJob {job.metadata.name} is being deleted")

        return can_adopt

    def _cached_candidates(self, informer, job: TFJob,
                           selector: Dict[str, str]) -> Optional[List]:
        """Claim candidates from the informer indices: everything we own
        (owner-UID index — includes release candidates whose labels no
        longer match) ∪ everything matching the job selector (selector
        index — includes adoptable orphans).  None when no synced informer
        is available and the caller must live-LIST."""
        if informer is None or not informer.has_synced:
            return None
        ns = job.metadata.namespace
        owned = informer.by_index(OWNER_UID_INDEX, job.metadata.uid)
        labeled = []
        keys = job_selector_index_keys(selector)
        for key in keys:
            labeled.extend(informer.by_index(JOB_SELECTOR_INDEX, key))
        seen: Dict[tuple, object] = {}
        for obj in owned + labeled:
            if obj.metadata.namespace == ns:
                seen[(ns, obj.metadata.name)] = obj
        return list(seen.values())

    def _gather_candidates(self, informer, client, job: TFJob,
                           selector: Dict[str, str]) -> List:
        cached = self._cached_candidates(informer, job, selector)
        if cached is not None and not has_adoption_candidates(cached, selector):
            if self.metrics is not None:
                self.metrics.inc_gather_indexed()
            # Candidates are shared cache references; claim() mutates on
            # adopt and callers partition/inspect them — copy first.
            return [serde.deep_copy(o) for o in cached]
        # Adoption pending (or no usable cache): list everything in the
        # namespace live, then claim — the reference always does this ("It
        # is a hack", helper.go:131-136) so adoption runs on fresh state.
        if self.metrics is not None:
            self.metrics.inc_gather_full_lists()
        return client.list(job.metadata.namespace)

    def get_pods_for_tfjob(self, job: TFJob, selector: Dict[str, str]) -> List[Pod]:
        pods = self._gather_candidates(self.pod_informer, self.cluster.pods,
                                       job, selector)
        mgr = RefManager(
            self.cluster.pods, job.metadata, KIND, API_VERSION,
            selector, self._can_adopt_fn(job),
        )
        return mgr.claim(pods)

    def get_services_for_tfjob(self, job: TFJob, selector: Dict[str, str]) -> List[Service]:
        services = self._gather_candidates(self.service_informer,
                                           self.cluster.services, job, selector)
        mgr = RefManager(
            self.cluster.services, job.metadata, KIND, API_VERSION,
            selector, self._can_adopt_fn(job),
        )
        return mgr.claim(services)
