"""Event recorder: the user-facing audit stream.

The reference broadcasts k8s Events (ref: pkg/controller/controller.go:107-110)
with reasons SuccessfulCreate / FailedCreate etc. (ref: pkg/controller/
control/types.go:20-29, emitted at control/service.go:72-84).  Here events are
recorded in-memory (queryable by tests and the CLI) and logged structurally —
the same three observability channels the reference has: logs, events, status
(SURVEY.md §5).
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field
from typing import List, Optional

logger = logging.getLogger("kubeflow_controller_tpu.events")

# Event reasons (ref: pkg/controller/control/types.go:20-29).
REASON_SUCCESSFUL_CREATE = "SuccessfulCreate"
REASON_FAILED_CREATE = "FailedCreate"
REASON_SUCCESSFUL_DELETE = "SuccessfulDelete"
REASON_FAILED_DELETE = "FailedDelete"

TYPE_NORMAL = "Normal"
TYPE_WARNING = "Warning"


@dataclass
class Event:
    object_kind: str
    object_key: str  # namespace/name
    type: str
    reason: str
    message: str
    timestamp: float = field(default_factory=time.time)
    count: int = 1


class EventRecorder:
    def __init__(self, component: str = "tfjob-controller", max_events: int = 4096):
        self.component = component
        self._lock = threading.Lock()
        self._events: List[Event] = []
        self._max = max_events

    def event(self, obj, event_type: str, reason: str, message: str) -> None:
        key = f"{obj.metadata.namespace}/{obj.metadata.name}"
        kind = getattr(obj, "kind", type(obj).__name__)
        with self._lock:
            # Aggregate identical consecutive events (broadcaster behavior).
            if self._events:
                last = self._events[-1]
                if (last.object_key, last.reason, last.message) == (key, reason, message):
                    last.count += 1
                    last.timestamp = time.time()
                    return
            self._events.append(Event(kind, key, event_type, reason, message))
            if len(self._events) > self._max:
                self._events = self._events[-self._max :]
        log = logger.info if event_type == TYPE_NORMAL else logger.warning
        log("event component=%s kind=%s object=%s reason=%s: %s",
            self.component, kind, key, reason, message)

    def events_for(self, namespace: str, name: str) -> List[Event]:
        key = f"{namespace}/{name}"
        with self._lock:
            return [e for e in self._events if e.object_key == key]

    def all_events(self) -> List[Event]:
        with self._lock:
            return list(self._events)
