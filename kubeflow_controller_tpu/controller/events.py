"""Event recorder: the user-facing audit stream.

The reference broadcasts k8s Events (ref: pkg/controller/controller.go:107-110)
with reasons SuccessfulCreate / FailedCreate etc. (ref: pkg/controller/
control/types.go:20-29, emitted at control/service.go:72-84).  Here events are
recorded in-memory (queryable by tests and the CLI) and logged structurally —
the same three observability channels the reference has: logs, events, status
(SURVEY.md §5).
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field
from typing import List, Optional

from ..utils import locks

logger = logging.getLogger("kubeflow_controller_tpu.events")

# Event reasons (ref: pkg/controller/control/types.go:20-29).
REASON_SUCCESSFUL_CREATE = "SuccessfulCreate"
REASON_FAILED_CREATE = "FailedCreate"
REASON_SUCCESSFUL_DELETE = "SuccessfulDelete"
REASON_FAILED_DELETE = "FailedDelete"
# Training-plane reasons (net-new: the progress plane's stall detector).
REASON_TRAINING_STALLED = "TrainingStalled"
REASON_TRAINING_RESUMED = "TrainingResumed"
# Capacity-plane reasons (net-new: the slice-contention gang scheduler).
REASON_GANG_QUEUED = "GangQueued"
REASON_GANG_ADMITTED = "GangAdmitted"
REASON_GANG_PREEMPTED = "GangPreempted"
# Recovery-plane reasons (net-new: the restart policy engine).
REASON_REPLICA_RESTARTED = "ReplicaRestarted"
REASON_BACKOFF_LIMIT_EXCEEDED = "BackoffLimitExceeded"
# Elastic-plane reasons (net-new: the width transition engine) — edge-
# triggered: one GangDegraded per shrink transition, one GangRestored
# when the gang returns to full width.
REASON_GANG_DEGRADED = "GangDegraded"
REASON_GANG_RESTORED = "GangRestored"
# Serving-plane reasons (net-new: the queue-depth autoscaler + graceful
# drain).  Scale events are edge-triggered per target change; one
# ServingDraining per replica entering its drain.
REASON_SERVING_SCALED_UP = "ServingScaledUp"
REASON_SERVING_SCALED_DOWN = "ServingScaledDown"
REASON_SERVING_DRAINING = "ServingDraining"
# Observability-plane reasons (net-new: the SLO burn-rate engine) — edge-
# triggered: one SLOBurn when both burn windows cross the threshold, one
# SLORecovered when the fast window falls back under it.
REASON_SLO_BURN = "SLOBurn"
REASON_SLO_RECOVERED = "SLORecovered"

TYPE_NORMAL = "Normal"
TYPE_WARNING = "Warning"


@dataclass
class Event:
    object_kind: str
    object_key: str  # namespace/name
    type: str
    reason: str
    message: str
    # Last-seen time; bumped on every aggregated repeat.
    timestamp: float = field(default_factory=time.time)
    # When this (object, reason, message) was FIRST recorded; the CLI's
    # event ages come from the last-seen clock, the ordering from this one.
    first_timestamp: float = 0.0
    count: int = 1
    # Aggregation override (e.g. the replica id for ReplicaRestarted):
    # repeats collapse on (object, reason, dedup_key) even as the message
    # text changes with the restart count.
    dedup_key: str = ""
    # Process-wide recording order (all_events sorts on it — per-object
    # rings make plain insertion order meaningless across objects).
    seq: int = 0

    def __post_init__(self):
        if not self.first_timestamp:
            self.first_timestamp = self.timestamp


class EventRecorder:
    def __init__(self, component: str = "tfjob-controller", max_events: int = 4096,
                 sink=None, per_object_max: int = 64):
        """``sink``: an events client (cluster.events) — when given, every
        event is ALSO written as a real Event API object, count-aggregated,
        visible via the API the way ``kubectl describe`` shows them (ref:
        broadcaster at pkg/controller/controller.go:107-110).  Best-effort,
        as in k8s: API failures never break the controller.

        Retention is a **per-object ring**: each object keeps its newest
        ``per_object_max`` deduplicated events, and ``max_events`` bounds
        the total across all rings (whole oldest-touched rings are evicted
        first).  A 10k-job create storm therefore neither grows event
        memory without bound NOR flushes every other job's audit trail —
        the flat-list retention both, before the scale envelope work."""
        import collections
        import queue

        self.component = component
        self._lock = locks.named_lock("events.recorder")
        # object_key -> ring of its newest events, oldest-touched key first
        # (move_to_end on every record keeps eviction LRU-by-object).
        self._rings: "collections.OrderedDict[str, collections.deque]" = (
            collections.OrderedDict())
        self._per_object_max = max(1, per_object_max)
        self._total = 0
        self._seq = 0
        # In-memory aggregation index: (object_key, reason, message) -> its
        # live Event.  Keyed, not last-element-only: interleaved events from
        # different jobs must not defeat dedup (a 20-job controller emits
        # SuccessfulCreate streams that interleave constantly).
        self._agg: dict = {}
        self._max = max_events
        self._sink = sink
        # Sink writes happen on ONE background flusher thread (the k8s
        # broadcaster model): recorder.event() in the sync path only
        # enqueues, so a slow API server never stalls reconciles on audit
        # traffic.  Bounded queue; overflow drops (best-effort stream).
        self._sink_queue: "queue.Queue" = queue.Queue(maxsize=1024)
        self._sink_names: dict = {}  # aggregate key -> Event object name
        self._sink_created: list = []  # (namespace, name) in creation order
        self._sink_thread = None
        self._closed = False
        if sink is not None:
            self._sink_thread = threading.Thread(
                target=self._sink_loop, name="event-sink", daemon=True)
            self._sink_thread.start()

    def event(self, obj, event_type: str, reason: str, message: str,
              dedup_key: str = "") -> None:
        """``dedup_key`` overrides the message in the aggregation key: a
        crash-looping replica's ReplicaRestarted events carry a changing
        count/backoff in the message, but must still collapse into ONE
        aggregated event per (job, reason, replica) — pass the replica id
        as the dedup key and the live event's message tracks the newest."""
        import collections

        key = f"{obj.metadata.namespace}/{obj.metadata.name}"
        kind = getattr(obj, "kind", type(obj).__name__)
        aggregated = False
        with self._lock:
            # Aggregate against the most recent event for the SAME
            # (object, reason, message-or-dedup-key) — broadcaster behavior,
            # keyed so interleavings across jobs cannot defeat it.
            # first_timestamp keeps the original sighting; timestamp tracks
            # the latest.
            agg_key = (key, reason, dedup_key or message)
            live = self._agg.get(agg_key)
            if live is not None:
                live.count += 1
                live.timestamp = time.time()
                live.message = message  # newest wording wins under dedup_key
                if key in self._rings:
                    self._rings.move_to_end(key)
                aggregated = True
            else:
                self._seq += 1
                ev = Event(kind, key, event_type, reason, message,
                           dedup_key=dedup_key, seq=self._seq)
                ring = self._rings.get(key)
                if ring is None:
                    ring = self._rings[key] = collections.deque()
                else:
                    self._rings.move_to_end(key)
                if len(ring) >= self._per_object_max:
                    self._drop_locked(ring.popleft())
                ring.append(ev)
                self._total += 1
                self._agg[agg_key] = ev
                # Global bound: evict whole rings, oldest-touched first —
                # one noisy job can age out, it cannot flush everyone.
                while self._total > self._max and self._rings:
                    old_key, old_ring = next(iter(self._rings.items()))
                    if old_key == key and len(self._rings) == 1:
                        self._drop_locked(old_ring.popleft())
                        if not old_ring:
                            del self._rings[old_key]
                        continue
                    for d in old_ring:
                        self._drop_locked(d, count=False)
                    self._total -= len(old_ring)
                    del self._rings[old_key]
        if not aggregated:
            log = logger.info if event_type == TYPE_NORMAL else logger.warning
            log("event component=%s kind=%s object=%s reason=%s: %s",
                self.component, kind, key, reason, message)
        if self._sink is not None and not self._closed:
            import queue

            try:
                self._sink_queue.put_nowait(
                    (kind, obj.metadata.namespace or "default",
                     obj.metadata.name, obj.metadata.uid,
                     key, event_type, reason, message, dedup_key))
            except queue.Full:
                pass  # drop under pressure: audit stream is best-effort

    def close(self, timeout: float = 5.0) -> None:
        """Drain pending sink writes and stop the flusher (idempotent).
        Without this, events recorded just before process exit would be
        lost in the queue."""
        import queue

        if self._sink_thread is None or self._closed:
            self._closed = True
            return
        self._closed = True
        try:
            # Bounded block: a healthy-but-backlogged flusher frees a slot
            # within the timeout (preserving the drain guarantee); a flusher
            # wedged on a hung API server does not, and we drop the sentinel
            # rather than hang shutdown — the daemon thread dies with the
            # process.
            self._sink_queue.put(None, timeout=timeout)
        except queue.Full:
            return
        self._sink_thread.join(timeout=timeout)

    def _sink_loop(self) -> None:
        while True:
            item = self._sink_queue.get()
            if item is None:
                return  # close(): everything enqueued before is drained
            try:
                self._write_sink(*item)
            except Exception:  # noqa: BLE001 — the flusher must survive
                logger.warning("event sink write failed", exc_info=True)

    def _write_sink(self, kind: str, ns: str, obj_name: str, uid: str,
                    key: str, event_type: str, reason: str,
                    message: str, dedup_key: str = "") -> None:
        """Runs ONLY on the flusher thread: no locking needed for the dedup
        index, and API latency never touches the sync path."""
        from ..api.core import EventObject, ObjectReference
        from ..cluster.store import APIError, NotFound

        agg = (key, reason, dedup_key or message)
        now = time.time()
        try:
            name = self._sink_names.get(agg)
            if name:
                try:
                    ev = self._sink.get(ns, name)
                    ev.count += 1
                    ev.last_timestamp = now
                    ev.message = message  # newest wording under dedup_key
                    self._sink.update(ev)
                    return
                except NotFound:
                    pass  # GC'd or restarted: recreate below
            ev = EventObject()
            ev.metadata.generate_name = f"{obj_name}."
            ev.metadata.namespace = ns
            ev.involved_object = ObjectReference(
                kind=kind, namespace=ns, name=obj_name, uid=uid)
            ev.type = event_type
            ev.reason = reason
            ev.message = message
            ev.first_timestamp = ev.last_timestamp = now
            ev.source_component = self.component
            created = self._sink.create(ev)
            # Bound both the dedup index (evict oldest entry, not the
            # whole map — clearing would recreate every aggregate) and
            # the stored objects (delete oldest: the TTL-expiry analog
            # real k8s applies to Events).
            if len(self._sink_names) >= self._max:
                self._sink_names.pop(next(iter(self._sink_names)))
            self._sink_names[agg] = created.metadata.name
            self._sink_created.append((ns, created.metadata.name))
            if len(self._sink_created) > self._max:
                old_ns, old_name = self._sink_created.pop(0)
                try:
                    self._sink.delete(old_ns, old_name)
                except APIError:
                    pass
        except APIError:
            pass  # best-effort audit stream

    def _drop_locked(self, d: Event, count: bool = True) -> None:
        """Forget one evicted event's aggregation entry (caller holds the
        lock); ``count`` adjusts the cross-ring total for single-event
        evictions (whole-ring eviction adjusts in bulk)."""
        k = (d.object_key, d.reason, d.dedup_key or d.message)
        if self._agg.get(k) is d:
            del self._agg[k]
        if count:
            self._total -= 1

    def events_for(self, namespace: str, name: str) -> List[Event]:
        key = f"{namespace}/{name}"
        with self._lock:
            return list(self._rings.get(key, ()))

    def all_events(self) -> List[Event]:
        with self._lock:
            out = [e for ring in self._rings.values() for e in ring]
        out.sort(key=lambda e: e.seq)
        return out
