"""Shared informer: local cache + indices + event handlers over a watch stream.

Semantic re-implementation of the client-go SharedIndexInformer machinery the
controller wires in its constructor (ref: pkg/controller/controller.go:98-165;
factories built with 30s resync at cmd/controller/main.go:62-63):

- initial LIST populates the cache and fires ADD handlers, after which
  ``has_synced`` is True (the ``WaitForCacheSync`` gate, controller.go:183);
- the WATCH loop keeps the cache fresh and fires add/update/delete handlers;
- a periodic **resync** re-fires update handlers for every cached object with
  old == new — the level-triggering backstop that re-drives reconciliation
  even if an edge was missed (update handlers can detect a resync by equal
  resourceVersions, as the reference does at controller.go:480-484);
- **indexers** (the cache.Indexers analog): ``add_indexer(name, fn)``
  registers a key function mapping an object to index keys; ``by_index``
  answers membership queries in O(bucket) instead of O(cache).  Indices are
  maintained under the cache lock on every mutation path (watch events,
  initial list, gap re-list), so a reader can never observe an object in the
  cache but missing from its index buckets.

Handlers run on the informer thread in event order — the same serialization
guarantee client-go provides a single event handler.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Set

from ..api.meta import key_of
from ..cluster.store import ADDED, DELETED, MODIFIED, Watcher
from ..obs.metrics import REGISTRY
from ..utils import locks


class SharedInformer:
    def __init__(self, client, resync_period_s: float = 30.0, name: str = ""):
        self._client = client
        self._resync_s = resync_period_s
        self.name = name or getattr(client, "kind", "objects")
        self._lock = locks.named_rlock(f"informer:{self.name}")
        self._cache: Dict[str, Any] = {}
        # index name -> index key -> set of cache keys; plus the reverse map
        # (cache key -> index name -> keys) so removal never recomputes keys
        # against a mutated object.
        self._indexers: Dict[str, Callable[[Any], List[str]]] = {}
        self._indices: Dict[str, Dict[str, Set[str]]] = {}
        self._obj_index_keys: Dict[str, Dict[str, List[str]]] = {}
        self._add_handlers: list[Callable[[Any], None]] = []
        self._update_handlers: list[Callable[[Any, Any], None]] = []
        self._delete_handlers: list[Callable[[Any], None]] = []
        self._synced = threading.Event()
        self._stop = threading.Event()
        self._watcher: Optional[Watcher] = None
        self._thread: Optional[threading.Thread] = None
        self._resync_thread: Optional[threading.Thread] = None
        # Full list+diff fallbacks: with an RV-resumable transport these
        # fire ONLY on a genuine 410-too-old gap — a climbing counter under
        # watch churn means resume points are going stale (watch cache too
        # small, or bookmarks not flowing).  `make churn-smoke` gates on 0.
        self._c_relists = REGISTRY.counter(
            "kctpu_watch_relists_total",
            "Informer full list+diff fallbacks after a non-resumable "
            "watch gap")

    # -- registration --------------------------------------------------------

    def add_event_handler(
        self,
        on_add: Optional[Callable[[Any], None]] = None,
        on_update: Optional[Callable[[Any, Any], None]] = None,
        on_delete: Optional[Callable[[Any], None]] = None,
    ) -> None:
        if on_add:
            self._add_handlers.append(on_add)
        if on_update:
            self._update_handlers.append(on_update)
        if on_delete:
            self._delete_handlers.append(on_delete)

    def add_indexer(self, name: str, fn: Callable[[Any], List[str]]) -> None:
        """Register an index (ref: cache.Indexers).  ``fn`` maps an object to
        zero or more index keys.  Registering after objects are cached
        back-fills the index from the current cache."""
        with self._lock:
            if name in self._indexers:
                raise ValueError(f"indexer {name!r} already registered")
            self._indexers[name] = fn
            self._indices[name] = {}
            for k, obj in self._cache.items():
                keys = self._index_keys_for(name, fn, obj)
                self._obj_index_keys.setdefault(k, {})[name] = keys
                for ik in keys:
                    self._indices[name].setdefault(ik, set()).add(k)

    # -- cache reads (the "lister") -----------------------------------------

    def get(self, namespace: str, name: str) -> Optional[Any]:
        with self._lock:
            return self._cache.get(f"{namespace}/{name}")

    def list(self) -> list:
        with self._lock:
            return list(self._cache.values())

    def by_index(self, name: str, index_key: str) -> list:
        """Cached objects whose indexer emitted ``index_key``
        (ref: Indexer.ByIndex).  Objects are shared cache references, like
        :meth:`list` — callers must deep-copy before mutating."""
        with self._lock:
            keys = self._indices[name].get(index_key, ())
            return [self._cache[k] for k in keys if k in self._cache]

    @property
    def has_synced(self) -> bool:
        return self._synced.is_set()

    def wait_for_cache_sync(self, timeout: float = 10.0) -> bool:
        return self._synced.wait(timeout)

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        # Open the watch BEFORE the initial list so no write is missed
        # between the two (list-then-watch with a gap would drop events).
        self._watcher = self._client.watch()
        for obj in self._client.list():
            k = key_of(obj.metadata)
            self._cache_set(k, obj)
            self._dispatch_add(obj)
        self._synced.set()
        self._thread = threading.Thread(target=self._watch_loop, name=f"informer-{self.name}", daemon=True)
        self._thread.start()
        if self._resync_s > 0:
            self._resync_thread = threading.Thread(
                target=self._resync_loop, name=f"informer-{self.name}-resync", daemon=True
            )
            self._resync_thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._watcher:
            self._watcher.stop()

    # -- internals -----------------------------------------------------------

    @staticmethod
    def _index_keys_for(name: str, fn: Callable[[Any], List[str]], obj: Any) -> List[str]:
        try:
            return list(fn(obj))
        except Exception:  # noqa: BLE001 — a broken indexer must not kill the watch loop
            return []

    def _cache_set(self, k: str, obj: Any) -> None:
        """Insert/replace a cache entry and rebuild its index postings, one
        critical section so index readers never see a half-applied update."""
        with self._lock:
            old_keys = self._obj_index_keys.pop(k, {})
            for name, keys in old_keys.items():
                idx = self._indices[name]
                for ik in keys:
                    bucket = idx.get(ik)
                    if bucket is not None:
                        bucket.discard(k)
                        if not bucket:
                            del idx[ik]
            self._cache[k] = obj
            if self._indexers:
                new_keys: Dict[str, List[str]] = {}
                for name, fn in self._indexers.items():
                    keys = self._index_keys_for(name, fn, obj)
                    new_keys[name] = keys
                    for ik in keys:
                        self._indices[name].setdefault(ik, set()).add(k)
                self._obj_index_keys[k] = new_keys

    def _cache_pop(self, k: str) -> Optional[Any]:
        with self._lock:
            obj = self._cache.pop(k, None)
            for name, keys in self._obj_index_keys.pop(k, {}).items():
                idx = self._indices[name]
                for ik in keys:
                    bucket = idx.get(ik)
                    if bucket is not None:
                        bucket.discard(k)
                        if not bucket:
                            del idx[ik]
            return obj

    def _watch_loop(self) -> None:
        # Transports that can drop events expose a `gaps` counter; a bump
        # means the stream was re-established WITHOUT a resume — anything
        # in between is lost, so re-list and diff, as client-go reflectors
        # do.  An RV-resumable transport (RestWatcher) replays missed
        # events on reconnect and only bumps `gaps` on a genuine
        # 410-too-old, keeping the full re-list strictly as the fallback.
        # The in-memory watcher resumes its own (bounded-queue) overflow
        # drops transparently and bumps `gaps` only when the overflow
        # window outran the watch cache — the in-process 410.
        seen_gaps = getattr(self._watcher, "gaps", 0)
        # Batch drain (store watchers expose next_batch): under a phase
        # storm the informer takes ONE queue-lock round-trip per batch of
        # events instead of one per event; transports without it (REST)
        # keep the single-event pop.
        next_batch = getattr(self._watcher, "next_batch", None)
        while not self._stop.is_set():
            gaps = getattr(self._watcher, "gaps", 0)
            if gaps != seen_gaps:
                seen_gaps = gaps
                # Drain events queued before/through the gap FIRST: a stale
                # pre-gap event applied after the re-list could resurrect an
                # object deleted during the gap (client-go flushes its FIFO
                # via Replace() for the same reason).  Anything drained that
                # was actually fresh (post-reconnect) is re-captured by the
                # list below, which reads newer state than those events.
                while self._watcher.next(timeout=0) is not None:
                    pass
                self._relist()
            if next_batch is not None:
                events = next_batch(max_n=256, timeout=0.2)
            else:
                ev = self._watcher.next(timeout=0.2)
                events = (ev,) if ev is not None else ()
            for ev in events:
                self._apply_event(ev)

    def _apply_event(self, ev) -> None:
        if ev.type not in (ADDED, MODIFIED, DELETED):
            return  # BOOKMARK etc.: transport checkpoints, no cache effect
        k = key_of(ev.object.metadata)
        if ev.type == ADDED:
            with self._lock:
                known = k in self._cache
                self._cache_set(k, ev.object)
            if known:
                # Already delivered by the initial list: treat as update.
                self._dispatch_update(ev.object, ev.object)
            else:
                self._dispatch_add(ev.object)
        elif ev.type == MODIFIED:
            with self._lock:
                old = self._cache.get(k, ev.object)
                self._cache_set(k, ev.object)
            self._dispatch_update(old, ev.object)
        elif ev.type == DELETED:
            self._cache_pop(k)
            self._dispatch_delete(ev.object)

    def _relist(self) -> None:
        """Full list + diff against the cache, firing the handlers the lost
        watch events would have fired."""
        try:
            fresh = {key_of(o.metadata): o for o in self._client.list()}
        except Exception:  # noqa: BLE001 — server still flapping; next gap retries
            return
        self._c_relists.inc()
        with self._lock:
            stale_keys = set(self._cache) - set(fresh)
        for k, obj in fresh.items():
            with self._lock:
                old = self._cache.get(k)
                self._cache_set(k, obj)
            if old is None:
                self._dispatch_add(obj)
            else:
                self._dispatch_update(old, obj)
        for k in stale_keys:
            gone = self._cache_pop(k)
            if gone is not None:
                self._dispatch_delete(gone)

    def _resync_loop(self) -> None:
        while not self._stop.is_set():
            if self._stop.wait(self._resync_s):
                return
            objs = self.list()
            if not objs:
                continue
            # Spread the dispatches across (half of) the resync window
            # instead of one synchronous burst: at N cached objects the
            # periodic enqueue spike becomes one dispatch per gap —
            # client-go jitters resync timing for the same reason.  Each
            # object is re-read from the cache at its turn (and skipped if
            # deleted meanwhile), so late dispatches see current state.
            gap = (self._resync_s * 0.5) / len(objs)
            for obj in objs:
                if self._stop.is_set():
                    return
                with self._lock:
                    cur = self._cache.get(key_of(obj.metadata))
                if cur is None:
                    continue  # deleted while spreading
                self._dispatch_update(cur, cur)
                if self._stop.wait(gap):
                    return

    def _dispatch_add(self, obj) -> None:
        for h in self._add_handlers:
            h(obj)

    def _dispatch_update(self, old, new) -> None:
        for h in self._update_handlers:
            h(old, new)

    def _dispatch_delete(self, obj) -> None:
        for h in self._delete_handlers:
            h(obj)
