"""Reconcile-latency metrics.

The reference has no metrics at all (SURVEY.md §5: glog only); the driver's
target metric includes reconcile p50 (BASELINE.json), so sync latency is
recorded here and exposed via percentiles — and, via :meth:`register`,
as a Prometheus summary + counters on the obs registry (``GET /metrics``).
"""

from __future__ import annotations

import collections
import random
import threading
from typing import Deque, Dict, List, Optional

from ..obs import metrics as obs_metrics
from ..utils import locks


class _Reservoir:
    """Bounded percentile window: a fixed-size uniform reservoir (Vitter's
    algorithm R) over ALL samples ever recorded, plus a fixed-size ring of
    the NEWEST samples for windowed queries ("p99 since the storm began").

    Before the scale envelope work the sample buffer grew to 100k floats
    per metric and truncation past the cap copied the whole list on every
    append — at 10k jobs that is O(n) per sync and tens of MB of floats.
    Here every sample costs O(1) time and the memory is a constant
    ``size + window`` floats regardless of job count.  Percentiles over
    the reservoir are unbiased estimates of the all-time distribution;
    windowed percentiles are exact while the queried window fits in the
    ring (bench storm windows are thousands; the ring holds 16k).

    NOT thread-safe: the owner serializes access (ReconcileMetrics lock)."""

    __slots__ = ("size", "_buf", "_recent", "count", "_rng")

    def __init__(self, size: int = 4096, window: int = 16384, seed: int = 0):
        self.size = size
        self._buf: List[float] = []
        self._recent: Deque[float] = collections.deque(maxlen=window)
        self.count = 0  # total samples ever offered
        self._rng = random.Random(seed)  # deterministic: benches reproduce

    def add(self, v: float) -> None:
        self.count += 1
        self._recent.append(v)
        if len(self._buf) < self.size:
            self._buf.append(v)
        else:
            j = self._rng.randrange(self.count)
            if j < self.size:
                self._buf[j] = v

    def sorted_all(self) -> List[float]:
        return sorted(self._buf)

    def sorted_since(self, start: int) -> List[float]:
        """Newest ``count - start`` samples (clamped to the ring)."""
        want = max(0, self.count - start)
        if want == 0:
            return []
        recent = list(self._recent)
        return sorted(recent[-want:])


class ReconcileMetrics:
    def __init__(self, max_samples: int = 4096):
        self._lock = locks.named_lock("controller.reconcile-metrics")
        self._samples = _Reservoir(size=max_samples)
        self._sum = 0.0  # cumulative, survives reservoir replacement
        self.syncs = 0
        self.sync_errors = 0
        self.creates = 0
        self.deletes = 0
        self.status_updates = 0
        # Gather-path split: syncs served from the informer indices vs.
        # full-namespace live LISTs (the adoption fallback).  The ratio is
        # the index hit rate — at steady state full_lists must be flat.
        self.gather_indexed = 0
        self.gather_full_lists = 0
        # Per-create API latency samples (pods+services), fed by the
        # Helper: the wide-job and multi-job benches share this one
        # latency vocabulary (create_latency_p50/p99 in snapshots).
        self._create_samples = _Reservoir(size=max_samples)

    def record_sync(self, duration_s: float, error: bool = False) -> None:
        with self._lock:
            self.syncs += 1
            if error:
                self.sync_errors += 1
            self._sum += duration_s
            self._samples.add(duration_s)

    # Counter increments from concurrent sync workers MUST go through these
    # (bare ``+= 1`` on the attributes is a lost-update race).
    def inc_creates(self, n: int = 1) -> None:
        with self._lock:
            self.creates += n

    def inc_deletes(self, n: int = 1) -> None:
        with self._lock:
            self.deletes += n

    def inc_status_updates(self, n: int = 1) -> None:
        with self._lock:
            self.status_updates += n

    def inc_gather_indexed(self, n: int = 1) -> None:
        with self._lock:
            self.gather_indexed += n

    def inc_gather_full_lists(self, n: int = 1) -> None:
        with self._lock:
            self.gather_full_lists += n

    def record_create_latency(self, duration_s: float) -> None:
        with self._lock:
            self._create_samples.add(duration_s)

    def create_latency_percentile(self, q: float) -> float:
        with self._lock:
            s = self._create_samples.sorted_all()
        if not s:
            return 0.0
        return s[min(len(s) - 1, int(q / 100.0 * len(s)))]

    def percentile(self, q: float) -> float:
        with self._lock:
            s = self._samples.sorted_all()
        if not s:
            return 0.0
        return s[min(len(s) - 1, int(q / 100.0 * len(s)))]

    # Windowed latency: benches that want "p99 during the storm" snapshot
    # sample_count() at the window start and read percentile_since(q, n).
    # Exact while the window fits in the reservoir's recent ring (16k; bench
    # storm windows are thousands).
    def sample_count(self) -> int:
        with self._lock:
            return self._samples.count

    def percentile_since(self, q: float, start: int) -> float:
        with self._lock:
            s = self._samples.sorted_since(start)
        if not s:
            return 0.0
        return s[min(len(s) - 1, int(q / 100.0 * len(s)))]

    @property
    def p50(self) -> float:
        return self.percentile(50)

    @property
    def p90(self) -> float:
        return self.percentile(90)

    @property
    def p99(self) -> float:
        return self.percentile(99)

    def snapshot(self) -> Dict[str, float]:
        # One lock hold, one sort per sample window: the per-percentile
        # properties each re-sorted the window, making a snapshot 5 sorts —
        # benches snapshot in their measurement loops, so this path is warm.
        with self._lock:
            samples = self._samples.sorted_all()
            creates = self._create_samples.sorted_all()
            out = {
                "syncs": self.syncs,
                "sync_errors": self.sync_errors,
                "creates": self.creates,
                "deletes": self.deletes,
                "status_updates": self.status_updates,
                "gather_indexed": self.gather_indexed,
                "gather_full_lists": self.gather_full_lists,
            }

        def q(s: List[float], p: float) -> float:
            if not s:
                return 0.0
            return s[min(len(s) - 1, int(p / 100.0 * len(s)))]

        out.update({
            "reconcile_p50_s": q(samples, 50),
            "reconcile_p90_s": q(samples, 90),
            "reconcile_p99_s": q(samples, 99),
            "create_latency_p50_s": q(creates, 50),
            "create_latency_p99_s": q(creates, 99),
            "samples": self._samples.count,
        })
        return out

    # -- Prometheus exposition ----------------------------------------------

    def register(self, registry: Optional[obs_metrics.Registry] = None,
                 key: str = "reconcile") -> None:
        """Expose this instance on the obs registry as a scrape-time
        collector: a quantile summary (percentiles over the sample window)
        plus cumulative counters.  Keyed, so the latest controller instance
        in a process owns the families."""
        reg = registry or obs_metrics.REGISTRY
        reg.register_collector(key, self._families)

    def _families(self) -> List[obs_metrics.Family]:
        with self._lock:
            samples = self._samples.sorted_all()
            total = self._sum
            syncs_n = self.syncs
            counters = [
                ("kctpu_controller_syncs_total", "Reconcile syncs executed",
                 self.syncs),
                ("kctpu_controller_sync_errors_total", "Reconcile syncs that raised",
                 self.sync_errors),
                ("kctpu_controller_creates_total", "Child pod/service creates",
                 self.creates),
                ("kctpu_controller_deletes_total", "Child pod/service deletes",
                 self.deletes),
                ("kctpu_controller_status_updates_total", "TFJob status writes",
                 self.status_updates),
                ("kctpu_gather_indexed_total",
                 "Child gathers served from the informer indices",
                 self.gather_indexed),
                ("kctpu_gather_full_lists_total",
                 "Child gathers that fell back to a full-namespace live LIST",
                 self.gather_full_lists),
            ]

        def q(p: float) -> float:
            if not samples:
                return 0.0
            return samples[min(len(samples) - 1, int(p * len(samples)))]

        summary = obs_metrics.Family(
            "kctpu_reconcile_duration_seconds", "summary",
            "Reconcile sync latency (quantiles over the sample window)",
            [obs_metrics.Sample("", {"quantile": "0.5"}, q(0.5)),
             obs_metrics.Sample("", {"quantile": "0.9"}, q(0.9)),
             obs_metrics.Sample("", {"quantile": "0.99"}, q(0.99)),
             obs_metrics.Sample("_sum", {}, total),
             obs_metrics.Sample("_count", {}, syncs_n)])
        fams = [summary]
        for name, help_text, value in counters:
            fams.append(obs_metrics.Family(
                name, "counter", help_text,
                [obs_metrics.Sample("", {}, float(value))]))
        return fams
