"""Reconcile-latency metrics.

The reference has no metrics at all (SURVEY.md §5: glog only); the driver's
target metric includes reconcile p50 (BASELINE.json), so sync latency is
recorded here and exposed via percentiles.
"""

from __future__ import annotations

import threading
from typing import Dict, List


class ReconcileMetrics:
    def __init__(self, max_samples: int = 100_000):
        self._lock = threading.Lock()
        self._samples: List[float] = []
        self._max = max_samples
        self.syncs = 0
        self.sync_errors = 0
        self.creates = 0
        self.deletes = 0
        self.status_updates = 0

    def record_sync(self, duration_s: float, error: bool = False) -> None:
        with self._lock:
            self.syncs += 1
            if error:
                self.sync_errors += 1
            self._samples.append(duration_s)
            if len(self._samples) > self._max:
                self._samples = self._samples[-self._max :]

    def percentile(self, q: float) -> float:
        with self._lock:
            if not self._samples:
                return 0.0
            s = sorted(self._samples)
            idx = min(len(s) - 1, int(q / 100.0 * len(s)))
            return s[idx]

    @property
    def p50(self) -> float:
        return self.percentile(50)

    @property
    def p90(self) -> float:
        return self.percentile(90)

    @property
    def p99(self) -> float:
        return self.percentile(99)

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            n = len(self._samples)
        return {
            "syncs": self.syncs,
            "sync_errors": self.sync_errors,
            "creates": self.creates,
            "deletes": self.deletes,
            "status_updates": self.status_updates,
            "reconcile_p50_s": self.p50,
            "reconcile_p90_s": self.p90,
            "reconcile_p99_s": self.p99,
            "samples": n,
        }
