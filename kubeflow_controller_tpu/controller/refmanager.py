"""Controller-ref manager: the adopt/release state machine.

Semantic re-implementation of the reference's Service ref manager
(ref: pkg/controller/ref/base.go:62-115, service.go:87-164 — itself a port of
k8s ``PodControllerRefManager``, controller_ref_manager.go:139-238), one
generic class for pods and services alike:

- owned by another controller -> skip;
- owned by us + selector match -> keep;
- owned by us + no match -> release (drop our ownerRef via metadata patch),
  unless we are being deleted;
- orphan + match -> adopt (append controller ownerRef), gated by a **live
  quorum read** re-checking our UID and deletionTimestamp
  (ref: RecheckDeletionTimestamp at controller_ref_manager.go:373-385,
  wired at pkg/controller/helper.go:137-148), memoized per claim pass
  (ref: sync.Once at base.go:38-45).

NotFound/Invalid on release are ignored: the object is gone or already
orphaned, which is the desired end state (ref: service.go:147-161).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

from ..api.meta import ObjectMeta, OwnerReference, get_controller_of, matches_selector
from ..cluster.store import APIError, NotFound


def has_adoption_candidates(objects, selector: Dict[str, str]) -> bool:
    """True when a claim pass over ``objects`` could ADOPT something: an
    orphan (no controller ownerRef), not being deleted, matching the
    selector.  The indexed gather fast path (helper.py) uses this to decide
    whether cached reads suffice or a live full LIST is required — adoption
    is the one transition that must run against fresh state, exactly like
    the reference's everything-listing hack (ref: helper.go:131-136)."""
    for obj in objects:
        if (
            get_controller_of(obj.metadata) is None
            and obj.metadata.deletion_timestamp is None
            and matches_selector(obj.metadata.labels, selector)
        ):
            return True
    return False


class RefManager:
    def __init__(
        self,
        client,  # typed client with patch_meta(ns, name, fn)
        controller_meta: ObjectMeta,
        controller_kind: str,
        controller_api_version: str,
        selector: Dict[str, str],
        can_adopt: Callable[[], None],  # raises to veto adoption
    ):
        self._client = client
        self.controller_meta = controller_meta
        self.controller_kind = controller_kind
        self.controller_api_version = controller_api_version
        self.selector = selector
        self._can_adopt = can_adopt
        self._can_adopt_result: Optional[Exception] = None
        self._can_adopt_ran = False

    def _check_can_adopt(self) -> None:
        """Memoized (the sync.Once of base.go:38-45)."""
        if not self._can_adopt_ran:
            self._can_adopt_ran = True
            try:
                self._can_adopt()
            except Exception as e:  # remember the veto for the whole pass
                self._can_adopt_result = e
        if self._can_adopt_result is not None:
            raise self._can_adopt_result

    def claim(self, objects: List) -> List:
        """Run the claim state machine over candidate objects; returns the
        objects this controller owns after adoption/release."""
        claimed = []
        errors: List[Exception] = []
        for obj in objects:
            try:
                if self._claim_object(obj):
                    claimed.append(obj)
            except APIError as e:
                errors.append(e)
        if errors:
            raise errors[0]
        return claimed

    def _claim_object(self, obj) -> bool:
        ref = get_controller_of(obj.metadata)
        matches = matches_selector(obj.metadata.labels, self.selector)
        if ref is not None:
            if ref.uid != self.controller_meta.uid:
                return False  # owned by someone else
            if matches:
                return True  # ours and matching: keep
            # Ours but selector no longer matches: release (unless deleting).
            if self.controller_meta.deletion_timestamp is not None:
                return False
            self._release(obj)
            return False
        # Orphan.
        if self.controller_meta.deletion_timestamp is not None or not matches:
            return False
        if obj.metadata.deletion_timestamp is not None:
            return False
        self._adopt(obj)
        return True

    def _controller_ref(self) -> OwnerReference:
        return OwnerReference(
            api_version=self.controller_api_version,
            kind=self.controller_kind,
            name=self.controller_meta.name,
            uid=self.controller_meta.uid,
            controller=True,
            block_owner_deletion=True,
        )

    def _adopt(self, obj) -> None:
        self._check_can_adopt()

        def patch(meta: ObjectMeta) -> None:
            if get_controller_of(meta) is not None:
                return  # raced: someone else adopted first
            meta.owner_references.append(self._controller_ref())

        self._client.patch_meta(obj.metadata.namespace, obj.metadata.name, patch)
        # Reflect the adoption on the in-memory candidate so the caller's
        # claimed list carries the ownerRef.
        obj.metadata.owner_references.append(self._controller_ref())

    def _release(self, obj) -> None:
        uid = self.controller_meta.uid

        def patch(meta: ObjectMeta) -> None:
            meta.owner_references = [r for r in meta.owner_references if r.uid != uid]

        try:
            self._client.patch_meta(obj.metadata.namespace, obj.metadata.name, patch)
        except NotFound:
            pass  # already gone: fine (ref: service.go:147-153)
