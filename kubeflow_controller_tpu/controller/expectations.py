"""Controller expectations: the create/observe race guard.

Semantic re-implementation of ``ControllerExpectationsInterface``
(ref: vendor/k8s.io/kubernetes/pkg/controller/controller_utils.go:136-285).
Between issuing a create and seeing its watch event, the informer cache
under-counts reality; without this cache a second sync would double-create
replicas.  The load-bearing contract (SURVEY.md §7 "hard parts"):

- ``satisfied_expectations(key)`` is True when the recorded expectation is
  **fulfilled** (adds <= 0 and dels <= 0, controller_utils.go:274-277) **or
  expired** (older than 5 minutes, controller_utils.go:205-207) or absent;
- observations may race ahead of expectations (counts can go negative —
  upstream explicitly allows this, controller_utils.go:258-270).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict

from ..utils import locks

EXPECTATION_TTL_S = 5 * 60.0  # ExpectationsTimeout, controller_utils.go:125


@dataclass
class _Expectation:
    adds: int = 0
    dels: int = 0
    timestamp: float = field(default_factory=time.time)

    def fulfilled(self) -> bool:
        return self.adds <= 0 and self.dels <= 0

    def expired(self, now: float, ttl: float) -> bool:
        return now - self.timestamp > ttl


class ControllerExpectations:
    def __init__(self, ttl_s: float = EXPECTATION_TTL_S):
        self._ttl = ttl_s
        self._lock = locks.named_lock("controller.expectations")
        self._store: Dict[str, _Expectation] = {}

    def expect_creations(self, key: str, count: int) -> None:
        with self._lock:
            self._store[key] = _Expectation(adds=count)

    def expect_deletions(self, key: str, count: int) -> None:
        with self._lock:
            self._store[key] = _Expectation(dels=count)

    def expect(self, key: str, adds: int, dels: int) -> None:
        """One sync may both create and delete (replacement plans)."""
        with self._lock:
            self._store[key] = _Expectation(adds=adds, dels=dels)

    def creation_observed(self, key: str) -> None:
        self._lower(key, add_delta=1)

    def deletion_observed(self, key: str) -> None:
        self._lower(key, del_delta=1)

    def lower_expectations(self, key: str, add_delta: int = 0, del_delta: int = 0) -> None:
        """Used when a create call fails outright: the watch event will never
        come, so decrement directly (ref: controller.go:381-383, 427-443)."""
        self._lower(key, add_delta, del_delta)

    def _lower(self, key: str, add_delta: int = 0, del_delta: int = 0) -> None:
        with self._lock:
            e = self._store.get(key)
            if e is not None:
                e.adds -= add_delta
                e.dels -= del_delta

    def satisfied_expectations(self, key: str) -> bool:
        with self._lock:
            e = self._store.get(key)
            if e is None:
                # No expectations recorded: a new controller or a new job —
                # sync (ref: controller_utils.go:194-200).
                return True
            return e.fulfilled() or e.expired(time.time(), self._ttl)

    def delete_expectations(self, key: str) -> None:
        with self._lock:
            self._store.pop(key, None)
