"""Rate-limited, deduplicating work queue.

Semantic re-implementation of client-go's ``workqueue`` (used at
pkg/controller/controller.go:132, 639):

- **dedup**: an item added while queued is collapsed; an item added while
  *being processed* is re-queued when ``done`` is called (never processed
  concurrently with itself — this is what serializes per-key syncs,
  ref: controller.go:72-76);
- **rate limiting**: ``add_rate_limited`` delays re-adds with per-item
  exponential backoff (base*2^failures up to a cap — the
  ItemExponentialFailureRateLimiter); ``forget`` resets the failure count
  on success (ref: controller.go:236-258 Forget-on-success / requeue-on-error);
- **shutdown**: ``shut_down`` drains waiters; ``get`` raises ShutDown.
"""

from __future__ import annotations

import heapq
import threading
import time
from typing import Dict, List, Optional, Set


class ShutDown(Exception):
    pass


class ItemExponentialFailureRateLimiter:
    def __init__(self, base_delay: float = 0.005, max_delay: float = 300.0):
        self.base_delay = base_delay
        self.max_delay = max_delay
        self._failures: Dict[str, int] = {}
        self._lock = threading.Lock()

    def when(self, item: str) -> float:
        with self._lock:
            n = self._failures.get(item, 0)
            self._failures[item] = n + 1
        return min(self.base_delay * (2 ** n), self.max_delay)

    def forget(self, item: str) -> None:
        with self._lock:
            self._failures.pop(item, None)

    def num_requeues(self, item: str) -> int:
        with self._lock:
            return self._failures.get(item, 0)


class RateLimitingQueue:
    def __init__(self, rate_limiter: Optional[ItemExponentialFailureRateLimiter] = None,
                 name: str = "tfJobs"):
        self.name = name
        self._limiter = rate_limiter or ItemExponentialFailureRateLimiter()
        self._cond = threading.Condition()
        self._queue: List[str] = []
        self._dirty: Set[str] = set()
        self._processing: Set[str] = set()
        # (ready_time, seq, item) min-heap for delayed adds.
        self._waiting: List[tuple] = []
        self._seq = 0
        self._shutting_down = False
        self._delay_thread = threading.Thread(
            target=self._delay_loop, name=f"wq-{name}-delay", daemon=True
        )
        self._delay_thread.start()

    # -- core add/get/done ---------------------------------------------------

    def add(self, item: str) -> None:
        with self._cond:
            if self._shutting_down or item in self._dirty:
                return
            self._dirty.add(item)
            if item in self._processing:
                return  # re-queued by done()
            self._queue.append(item)
            self._cond.notify()

    def get(self, timeout: Optional[float] = None) -> Optional[str]:
        """Blocks for the next item; None on timeout; raises ShutDown when
        the queue is drained and shutting down."""
        with self._cond:
            deadline = None if timeout is None else time.time() + timeout
            while not self._queue:
                if self._shutting_down:
                    raise ShutDown()
                remaining = None if deadline is None else deadline - time.time()
                if remaining is not None and remaining <= 0:
                    return None
                self._cond.wait(timeout=remaining)
            item = self._queue.pop(0)
            self._processing.add(item)
            self._dirty.discard(item)
            return item

    def done(self, item: str) -> None:
        with self._cond:
            self._processing.discard(item)
            if item in self._dirty:
                self._queue.append(item)
                self._cond.notify()

    # -- rate limiting -------------------------------------------------------

    def add_rate_limited(self, item: str) -> None:
        self.add_after(item, self._limiter.when(item))

    def add_after(self, item: str, delay: float) -> None:
        if delay <= 0:
            self.add(item)
            return
        with self._cond:
            if self._shutting_down:
                return
            self._seq += 1
            heapq.heappush(self._waiting, (time.time() + delay, self._seq, item))
            self._cond.notify()

    def forget(self, item: str) -> None:
        self._limiter.forget(item)

    def num_requeues(self, item: str) -> int:
        return self._limiter.num_requeues(item)

    def _delay_loop(self) -> None:
        while True:
            with self._cond:
                if self._shutting_down and not self._waiting:
                    return
                now = time.time()
                while self._waiting and self._waiting[0][0] <= now:
                    _, _, item = heapq.heappop(self._waiting)
                    if item not in self._dirty and not self._shutting_down:
                        self._dirty.add(item)
                        if item not in self._processing:
                            self._queue.append(item)
                            self._cond.notify()
                wait = 0.05
                if self._waiting:
                    wait = min(wait, max(0.0, self._waiting[0][0] - now))
            time.sleep(wait if wait > 0 else 0.001)

    # -- lifecycle -----------------------------------------------------------

    def shut_down(self) -> None:
        with self._cond:
            self._shutting_down = True
            self._cond.notify_all()

    def __len__(self) -> int:
        with self._cond:
            return len(self._queue)
