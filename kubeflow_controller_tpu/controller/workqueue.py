"""Rate-limited, deduplicating work queue.

Semantic re-implementation of client-go's ``workqueue`` (used at
pkg/controller/controller.go:132, 639):

- **dedup**: an item added while queued is collapsed; an item added while
  *being processed* is re-queued when ``done`` is called (never processed
  concurrently with itself — this is what serializes per-key syncs,
  ref: controller.go:72-76);
- **priority tiers**: ``add(item, low=True)`` queues into a LOW tier that
  workers drain only when the fresh tier is empty (with a 1-in-8
  anti-starvation pop so the low tier always makes progress).  Resyncs and
  stall-timer re-enqueues ride the low tier: during a 10k-job storm the
  periodic level-triggered backstop would otherwise interleave with (and
  at scale, bury) the watch-edge work that actually advances jobs.  A
  fresh ``add`` of an item sitting in the low tier promotes it;
- **per-tenant fairness**: the fresh tier is one FIFO *per tenant*
  (``tenant_of(item)``; default: the key's namespace), drained
  round-robin — one tenant churning 10k watch edges cannot bury another
  tenant's single edge behind them, so a victim tenant's reconcile
  latency stays flat under a noisy neighbor's storm (``bench.py
  --tenants`` gates the p99);
- **rate limiting**: ``add_rate_limited`` delays re-adds with per-item
  exponential backoff (base*2^failures up to a cap — the
  ItemExponentialFailureRateLimiter); ``forget`` resets the failure count
  on success (ref: controller.go:236-258 Forget-on-success / requeue-on-error);
- **shutdown**: ``shut_down`` drains waiters; ``get`` raises ShutDown;
- **instrumentation** (client-go's workqueue metrics provider, which the
  reference never wired): depth gauge, adds/retries/requeues counters, and
  a queue-wait histogram (add→get latency), all labeled by queue name in
  the process-global obs registry.
"""

from __future__ import annotations

import collections
import heapq
import threading
import time
from typing import Callable, Deque, Dict, List, Optional, Set


def _namespace_tenant(item: str) -> str:
    """Default tenant resolver for "namespace/name" keys.  The controller
    overrides this with a label-aware resolver (api/tenant.tenant_of on
    the watched job); the namespace is the same default that resolver
    falls back to."""
    return item.split("/", 1)[0] if "/" in item else "default"

from ..obs import metrics as obs_metrics
from ..utils import locks


class _QueueMetrics:
    """Per-queue handles into the (shared, get-or-create) instruments."""

    def __init__(self, name: str, registry: Optional[obs_metrics.Registry] = None):
        reg = registry or obs_metrics.REGISTRY
        self.depth = reg.gauge(
            "kctpu_workqueue_depth",
            "Items currently queued (not yet handed to a worker)",
            labelnames=("name",)).labels(name=name)
        self.adds = reg.counter(
            "kctpu_workqueue_adds_total",
            "Items accepted into the queue (dedup-collapsed adds excluded)",
            labelnames=("name",)).labels(name=name)
        self.retries = reg.counter(
            "kctpu_workqueue_retries_total",
            "Rate-limited re-adds after sync errors",
            labelnames=("name",)).labels(name=name)
        self.requeues = reg.counter(
            "kctpu_workqueue_requeues_total",
            "Items re-queued by done() because they went dirty mid-processing",
            labelnames=("name",)).labels(name=name)
        self.queue_wait = reg.histogram(
            "kctpu_workqueue_queue_duration_seconds",
            "Seconds an item waited in the queue before a worker took it",
            labelnames=("name",)).labels(name=name)


class ShutDown(Exception):
    pass


class ItemExponentialFailureRateLimiter:
    def __init__(self, base_delay: float = 0.005, max_delay: float = 300.0):
        self.base_delay = base_delay
        self.max_delay = max_delay
        self._failures: Dict[str, int] = {}
        self._lock = locks.named_lock("workqueue.limiter")

    def when(self, item: str) -> float:
        with self._lock:
            n = self._failures.get(item, 0)
            self._failures[item] = n + 1
        return min(self.base_delay * (2 ** n), self.max_delay)

    def forget(self, item: str) -> None:
        with self._lock:
            self._failures.pop(item, None)

    def num_requeues(self, item: str) -> int:
        with self._lock:
            return self._failures.get(item, 0)


class RateLimitingQueue:
    def __init__(self, rate_limiter: Optional[ItemExponentialFailureRateLimiter] = None,
                 name: str = "tfJobs",
                 registry: Optional[obs_metrics.Registry] = None,
                 tenant_of: Optional[Callable[[str], str]] = None):
        self.name = name
        self._limiter = rate_limiter or ItemExponentialFailureRateLimiter()
        self._metrics = _QueueMetrics(name, registry)
        self._tenant_of = tenant_of or _namespace_tenant
        # One lock, two wait-sets: workers blocked in get() wait on _cond;
        # the delay thread waits on _delay_cond until the earliest deadline
        # or an add_after() notify.  Separate conditions so a notify can
        # never be eaten by the wrong waiter (a single shared condition
        # with notify(1) could wake a get() waiter instead of the delay
        # loop and lose the wakeup).
        self._lock = locks.named_lock(f"workqueue:{name}")
        self._cond = locks.named_condition(f"workqueue:{name}", self._lock)
        self._delay_cond = locks.named_condition(f"workqueue:{name}",
                                                 self._lock)
        # Fresh tier: one FIFO deque PER TENANT plus a round-robin ring of
        # tenant names, so the pop hot path stays O(1) (deque popleft +
        # ring rotate) while no tenant's storm can sit in front of another
        # tenant's single item.  A tenant appears in the ring at most once
        # (_rr_set guards); emptied tenants drop out of the ring lazily.
        self._fresh: Dict[str, Deque[str]] = {}
        self._rr: Deque[str] = collections.deque()
        self._rr_set: Set[str] = set()
        self._fresh_n = 0
        # LOW tier (resyncs / stall-timer backstops).  Items present here
        # are tracked in _low; promotion leaves a stale deque entry behind
        # that get() skips (lazy deletion — O(1) promote, no deque scan).
        self._queue_low: Deque[str] = collections.deque()
        self._low: Set[str] = set()
        # Items that went dirty *while processing* via a low add: done()
        # requeues them into the low tier instead of the fresh one.
        self._low_pending: Set[str] = set()
        self._gets = 0  # anti-starvation clock for the low tier
        self._dirty: Set[str] = set()
        self._processing: Set[str] = set()
        # Enqueue wall-clock per queued item, for the queue-wait histogram.
        self._enqueued_at: Dict[str, float] = {}
        # (ready_time, seq, item) min-heap for delayed adds.
        self._waiting: List[tuple] = []
        self._seq = 0
        self._shutting_down = False
        self._delay_thread = threading.Thread(
            target=self._delay_loop, name=f"wq-{name}-delay", daemon=True
        )
        self._delay_thread.start()

    # -- core add/get/done ---------------------------------------------------

    def add(self, item: str, low: bool = False) -> None:
        with self._cond:
            if self._shutting_down:
                return
            if item in self._dirty:
                if not low and item in self._low:
                    # Fresh edge for an item parked in the low tier:
                    # promote (lazy-delete the low entry).
                    self._low.discard(item)
                    self._low_pending.discard(item)
                    if item not in self._processing:
                        self._push_fresh_locked(item)
                        self._cond.notify()
                return
            self._dirty.add(item)
            self._metrics.adds.inc()
            if item in self._processing:
                if low:
                    self._low_pending.add(item)
                return  # re-queued by done()
            if low:
                self._low.add(item)
                self._queue_low.append(item)
            else:
                self._push_fresh_locked(item)
            self._enqueued_at.setdefault(item, time.time())
            self._metrics.depth.set(self._depth_locked())
            self._cond.notify()

    def _depth_locked(self) -> int:
        return self._fresh_n + len(self._low)

    def _push_fresh_locked(self, item: str) -> None:
        tenant = self._tenant_of(item)
        self._fresh.setdefault(tenant, collections.deque()).append(item)
        self._fresh_n += 1
        if tenant not in self._rr_set:
            self._rr_set.add(tenant)
            self._rr.append(tenant)

    def _pop_fresh_locked(self) -> Optional[str]:
        """Round-robin across tenant FIFOs: pop the front tenant's oldest
        item, rotate the tenant to the back if it still has work, drop it
        from the ring if not."""
        while self._rr:
            tenant = self._rr.popleft()
            dq = self._fresh.get(tenant)
            if not dq:
                self._rr_set.discard(tenant)
                continue
            item = dq.popleft()
            self._fresh_n -= 1
            if dq:
                self._rr.append(tenant)
            else:
                self._rr_set.discard(tenant)
            return item
        return None

    def _pop_low_locked(self) -> Optional[str]:
        dq = self._queue_low
        while dq:
            item = dq.popleft()
            if item not in self._low:
                continue  # promoted or claimed: stale entry
            self._low.discard(item)
            return item
        return None

    def _pop_locked(self) -> Optional[str]:
        """Next ready item across tiers: fresh first, low when fresh is
        empty — except every 8th pop prefers low, so a sustained storm of
        fresh edges cannot starve the level-triggered backstop forever."""
        self._gets += 1
        if (self._gets & 7) == 0:
            item = self._pop_low_locked()
            if item is None:
                item = self._pop_fresh_locked()
        else:
            item = self._pop_fresh_locked()
            if item is None:
                item = self._pop_low_locked()
        return item

    def get(self, timeout: Optional[float] = None) -> Optional[str]:
        """Blocks for the next item; None on timeout; raises ShutDown when
        the queue is drained and shutting down."""
        with self._cond:
            deadline = None if timeout is None else time.time() + timeout
            while True:
                item = self._pop_locked()
                if item is not None:
                    break
                if self._shutting_down:
                    raise ShutDown()
                remaining = None if deadline is None else deadline - time.time()
                if remaining is not None and remaining <= 0:
                    return None
                self._cond.wait(timeout=remaining)
            self._processing.add(item)
            self._dirty.discard(item)
            t_add = self._enqueued_at.pop(item, None)
            self._metrics.depth.set(self._depth_locked())
            if t_add is not None:
                self._metrics.queue_wait.observe(max(0.0, time.time() - t_add))
            return item

    def done(self, item: str) -> None:
        with self._cond:
            self._processing.discard(item)
            if item in self._dirty:
                if item in self._low_pending:
                    self._low_pending.discard(item)
                    self._low.add(item)
                    self._queue_low.append(item)
                else:
                    self._push_fresh_locked(item)
                self._enqueued_at.setdefault(item, time.time())
                self._metrics.depth.set(self._depth_locked())
                self._metrics.requeues.inc()
                self._cond.notify()

    # -- rate limiting -------------------------------------------------------

    def add_rate_limited(self, item: str) -> None:
        self._metrics.retries.inc()
        self.add_after(item, self._limiter.when(item))

    def add_after(self, item: str, delay: float) -> None:
        if delay <= 0:
            self.add(item)
            return
        with self._cond:
            if self._shutting_down:
                return
            self._seq += 1
            heapq.heappush(self._waiting, (time.time() + delay, self._seq, item))
            # Wake the delay thread: the new deadline may be earlier than
            # the one it is currently sleeping toward.
            self._delay_cond.notify()

    def forget(self, item: str) -> None:
        self._limiter.forget(item)

    def num_requeues(self, item: str) -> int:
        return self._limiter.num_requeues(item)

    def _delay_loop(self) -> None:
        # Event-driven, not polled: sleeps on the condition until the
        # earliest deadline (or an add_after/shutdown notify).  The old
        # 50 ms poll woke 20×/s on an idle queue and added up to 50 ms of
        # latency to every delayed re-add; now a re-add fires at its
        # deadline and an empty _waiting set costs zero wakeups.
        with self._delay_cond:
            while not self._shutting_down:
                now = time.time()
                while self._waiting and self._waiting[0][0] <= now:
                    _, _, item = heapq.heappop(self._waiting)
                    if item in self._dirty:
                        continue  # dedup: already queued (or pending requeue)
                    self._dirty.add(item)
                    self._metrics.adds.inc()
                    if item not in self._processing:
                        self._push_fresh_locked(item)
                        self._enqueued_at.setdefault(item, time.time())
                        self._metrics.depth.set(self._depth_locked())
                        self._cond.notify()
                timeout = None
                if self._waiting:
                    timeout = max(0.0, self._waiting[0][0] - now)
                self._delay_cond.wait(timeout=timeout)

    # -- shard handoff support (ha/shards.py) --------------------------------

    def drain_pending(self) -> List[tuple]:
        """Atomically claim every item not currently being processed —
        ready FIFO, delayed heap, and the dirty flags of items queued
        behind an in-flight sync — and return ``(item, ready_at)`` pairs
        (``ready_at`` 0.0 = ready now, else the absolute deadline).

        After this call the queue holds only its in-flight syncs: a
        ``done()`` on them will NOT requeue (their dirty flag was
        claimed), which is exactly what a shard handoff needs — the new
        owner re-adds the claimed keys and per-key ordering is preserved
        by waiting out the in-flight syncs before the re-add."""
        with self._cond:
            out = []
            # Fresh items in the same tenant-interleaved order a worker
            # would have drained them (ring order, one per tenant per
            # round) so the new owner preserves inter-tenant fairness.
            fresh = {t: collections.deque(dq)
                     for t, dq in self._fresh.items() if dq}
            ring = collections.deque(t for t in self._rr if t in fresh)
            seen = set(ring)
            ring.extend(t for t in fresh if t not in seen)
            while ring:
                t = ring.popleft()
                dq = fresh[t]
                if not dq:
                    continue
                out.append((dq.popleft(), 0.0))
                if dq:
                    ring.append(t)
            out.extend((item, 0.0) for item in self._queue_low
                       if item in self._low)
            self._fresh.clear()
            self._rr.clear()
            self._rr_set.clear()
            self._fresh_n = 0
            self._queue_low.clear()
            self._low.clear()
            self._low_pending.clear()
            out.extend((item, ready_at) for ready_at, _, item in self._waiting)
            self._waiting = []
            # Remaining dirty after removing the ready items = items that
            # went dirty while in-flight (done() would have requeued them).
            queued = {item for item, _ in out}
            out.extend((item, 0.0) for item in self._dirty if item not in queued)
            self._dirty.clear()
            self._enqueued_at.clear()
            self._metrics.depth.set(0)
            return out

    def processing_snapshot(self) -> Set[str]:
        """Keys currently inside a worker's sync (racy by nature; used by
        the shard-handoff quiesce loop, which re-polls)."""
        with self._cond:
            return set(self._processing)

    # -- lifecycle -----------------------------------------------------------

    def shut_down(self) -> None:
        with self._cond:
            self._shutting_down = True
            self._cond.notify_all()
            self._delay_cond.notify_all()

    def __len__(self) -> int:
        with self._cond:
            return self._depth_locked()
