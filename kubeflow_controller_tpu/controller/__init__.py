"""The reconcile engine (ref: pkg/controller/).

Primitives first (workqueue, expectations, informer — the vendored k8s
machinery of SURVEY.md §2.3 re-implemented idiomatically), then the
controller loop itself.
"""

from .workqueue import RateLimitingQueue, ShutDown  # noqa: F401
from .expectations import ControllerExpectations  # noqa: F401
from .informer import SharedInformer  # noqa: F401
from .events import EventRecorder, Event  # noqa: F401
from .helper import Helper  # noqa: F401
from .refmanager import RefManager  # noqa: F401
from .metrics import ReconcileMetrics  # noqa: F401
from .controller import Controller  # noqa: F401
