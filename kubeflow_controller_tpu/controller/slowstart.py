"""Slow-start batched plan execution — client-go's ``slowStartBatch``.

Semantic re-implementation of the pattern job-controller and replicaset use
for wide fan-out (ref: vendor/k8s.io/kubernetes/pkg/controller/
job/job_controller.go ``slowStartBatch``): dispatch work in exponentially
growing batches (1, 2, 4, 8, …) so that

- a *healthy* wide job reaches full parallelism after O(log n) rounds and
  the tail runs flat-out, while
- a *persistently failing* call (quota exhausted, forbidden, invalid
  template) costs O(log n) wasted calls instead of n: the first batch with
  an error stops new batches from launching — in-flight calls drain, their
  errors are aggregated, and the skipped remainder is reported back so the
  caller can settle its expectation accounting.

Differences from client-go, by design:

- the unit of work is an *item* (a plan event), not an opaque closure, so
  callers get back exactly which items were never attempted;
- every error in the failing batch is kept (aggregated into
  :class:`ManageError` by the controller), not just the first — a wide
  batch failing for two different reasons should say so;
- execution runs on a caller-supplied bounded ``ThreadPoolExecutor`` shared
  across syncs (the ``--manage-workers`` knob), not unbounded goroutines.
"""

from __future__ import annotations

from contextlib import nullcontext
from typing import Callable, ContextManager, List, Optional, Sequence, Tuple

#: First batch size (client-go SlowStartInitialBatchSize).
INITIAL_BATCH_SIZE = 1


class ManageError(Exception):
    """Aggregate of every error one plan execution produced.

    ``errors`` preserves the individual exceptions; ``attempted`` counts the
    events actually dispatched and ``skipped`` the events slow-start never
    launched (their expectations were already lowered by the caller)."""

    def __init__(self, errors: Sequence[BaseException],
                 attempted: int = 0, skipped: int = 0):
        self.errors = list(errors)
        self.attempted = attempted
        self.skipped = skipped
        head = "; ".join(str(e) for e in self.errors[:3])
        more = (f" (+{len(self.errors) - 3} more)"
                if len(self.errors) > 3 else "")
        super().__init__(
            f"{len(self.errors)}/{attempted} plan events failed"
            f" ({skipped} skipped): {head}{more}")


def slow_start_batch(
    items: Sequence,
    fn: Callable,
    executor=None,
    initial_batch_size: int = INITIAL_BATCH_SIZE,
    batch_cm: Optional[Callable[[int], ContextManager]] = None,
) -> Tuple[int, List[BaseException], List]:
    """Run ``fn(item)`` over ``items`` in exponentially growing batches.

    Returns ``(successes, errors, skipped_items)``.  A batch containing any
    error stops *new* batches from launching; every call already dispatched
    in that batch still drains (so its side effects — and its expectation
    accounting — are real).  ``executor=None`` runs batches inline, which
    keeps the serial (``--manage-workers 1``) path byte-identical in call
    order to the historical one-loop execution.

    ``batch_cm(n)`` (optional) is entered around each batch's
    dispatch+drain — the controller hangs its ``sync/manage/batch`` trace
    span and the ``kctpu_manage_batch_size`` histogram observation off it.
    """
    items = list(items)
    successes = 0
    errors: List[BaseException] = []
    pos = 0
    batch = min(len(items), max(1, initial_batch_size))
    while pos < len(items) and not errors:
        chunk = items[pos:pos + batch]
        cm = batch_cm(len(chunk)) if batch_cm is not None else nullcontext()
        with cm:
            if executor is None or len(chunk) == 1:
                # Inline: the serial knob, and the 1-item probe batch (a
                # thread hop would only add latency to the failure probe).
                for it in chunk:
                    try:
                        fn(it)
                        successes += 1
                    except Exception as e:  # noqa: BLE001 — aggregated
                        errors.append(e)
            else:
                futures = [executor.submit(fn, it) for it in chunk]
                for f in futures:  # drain ALL in-flight, even after errors
                    e = f.exception()
                    if e is None:
                        successes += 1
                    else:
                        errors.append(e)
        pos += len(chunk)
        batch = min(batch * 2, len(items) - pos)
    return successes, errors, items[pos:]
