"""Fake HTTP API server: the in-memory ObjectStore behind real HTTP.

The test backend for the REST transport (cluster/rest.py) — the HTTP-level
analog of the fake clientset the reference's generated code ships for
controller tests (ref: clientset/versioned/fake/clientset_generated.go:
33-46 over an ObjectTracker).  Same store, same semantics (resourceVersion
conflicts, generateName, watch ordering, cascade GC); what's added is the
wire: URL routing, JSON bodies, k8s Status errors, merge patches, and
streaming watch responses.

Run an in-process server, point a RestCluster at ``http://127.0.0.1:port``,
and the controller exercises the exact code path it would use against a
live API server.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional, Tuple, Type
from urllib.parse import parse_qs, urlparse

from ..api.core import EventObject, Lease, Pod, Service, TenantQuota
from ..api.tfjob import TFJob
from ..obs.metrics import REGISTRY
from ..utils import locks, serde
from .rest import CORE_API, TFJOB_API, TFJOB_GROUP, TFJOB_VERSION
from .store import (
    BOOKMARK,
    AlreadyExists,
    APIError,
    Conflict,
    Invalid,
    NotFound,
    ObjectStore,
    TooOldResourceVersion,
)

_KINDS: Dict[str, Tuple[Type, str, str]] = {
    # plural -> (dataclass, apiVersion, Kind)
    "tfjobs": (TFJob, f"{TFJOB_GROUP}/{TFJOB_VERSION}", "TFJob"),
    "pods": (Pod, "v1", "Pod"),
    "services": (Service, "v1", "Service"),
    "events": (EventObject, "v1", "Event"),
    # Leader-election coordination object (ha/lease.py); served under the
    # core prefix for routing simplicity — the fake API server does not
    # model API groups beyond the tfjobs CRD split.
    "leases": (Lease, "coordination.k8s.io/v1", "Lease"),
    # Per-tenant fair-share contract (api/core.py TenantQuotaSpec); like
    # leases, served under the core prefix for routing simplicity.
    "tenantquotas": (TenantQuota, "kubeflow.caicloud.io/v1alpha1",
                     "TenantQuota"),
}

#: Fencing token header (docs/HA.md): writes from a fenced REST client
#: carry the leader generation; the store rejects stale tokens.
FENCE_HEADER = "X-Kctpu-Fence"

#: Tenant identity header on write requests: lets the apiserver bill a
#: mutating request to the caller's tenant even when the object path's
#: namespace is not the tenant (multi-tenant namespaces).  Absent, the
#: route namespace is billed.
TENANT_HEADER = "X-Kctpu-Tenant"

#: HTTP methods the per-tenant write throttle gates.  Reads stay
#: unthrottled: list/watch pressure is the informer plane's problem and
#: already bounded by the watch cache.
_WRITE_METHODS = frozenset({"POST", "PUT", "PATCH", "DELETE"})


class _TokenBucket:
    """One tenant's write budget: ``rate`` tokens/s up to ``burst``.
    Monotonic-clock refill; take() returns 0.0 on admit, else the
    seconds until one token is available (the Retry-After hint)."""

    __slots__ = ("rate", "burst", "tokens", "last")

    def __init__(self, rate: float, burst: float):
        self.rate = rate
        self.burst = burst
        self.tokens = burst
        self.last = time.monotonic()

    def take(self) -> float:
        now = time.monotonic()
        self.tokens = min(self.burst, self.tokens + (now - self.last) * self.rate)
        self.last = now
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            return 0.0
        return (1.0 - self.tokens) / self.rate if self.rate > 0 else 1.0


def _parse_selector(q: Dict[str, list]) -> Optional[Dict[str, str]]:
    """Equality selectors only (k=v / k==v) — what the controller's label
    scheme uses.  Set-based / inequality operators are rejected loudly
    rather than silently matching the wrong objects."""
    raw = (q.get("labelSelector") or [None])[0]
    if not raw:
        return None
    out = {}
    for part in raw.split(","):
        part = part.strip()
        if not part:
            continue
        if "!=" in part or part.endswith(" in") or " in " in part or " notin " in part:
            raise Invalid(f"unsupported label selector operator in {part!r}; "
                          "only equality (k=v) is supported")
        if "==" in part:
            k, v = part.split("==", 1)
        elif "=" in part:
            k, v = part.split("=", 1)
        else:
            raise Invalid(f"cannot parse label selector clause {part!r}")
        out[k.strip()] = v.strip()
    return out


def _status(code: int, reason: str, message: str) -> Tuple[int, dict]:
    return code, {
        "kind": "Status", "apiVersion": "v1", "status": "Failure",
        "reason": reason, "message": message, "code": code,
    }


def _error_status(e: APIError) -> Tuple[int, dict]:
    if isinstance(e, TooOldResourceVersion):
        # 410 Gone, reason Expired — what the real apiserver returns for a
        # watch resourceVersion older than its watch cache.
        return _status(410, "Expired", str(e))
    if isinstance(e, NotFound):
        return _status(404, "NotFound", str(e))
    if isinstance(e, AlreadyExists):
        return _status(409, "AlreadyExists", str(e))
    if isinstance(e, Conflict):
        return _status(409, "Conflict", str(e))
    if isinstance(e, Invalid):
        return _status(422, "Invalid", str(e))
    return _status(500, "InternalError", str(e))


class _Route:
    """Parsed request path: collection or item, which kind, namespace."""

    def __init__(self, plural: str, namespace: Optional[str],
                 name: Optional[str], subresource: Optional[str],
                 watch: bool, selector: Optional[Dict[str, str]],
                 tail_lines: int = 0, resource_version: Optional[str] = None):
        self.plural = plural
        self.namespace = namespace
        self.name = name
        self.subresource = subresource
        self.watch = watch
        self.selector = selector
        self.tail_lines = tail_lines
        self.resource_version = resource_version


def _route(path: str, query: str) -> Optional[_Route]:
    q = parse_qs(query)
    for prefix in (TFJOB_API, CORE_API):
        if not path.startswith(prefix + "/"):
            continue
        parts = [p for p in path[len(prefix):].split("/") if p]
        ns = None
        if parts and parts[0] == "namespaces":
            if len(parts) < 3:
                return None
            ns = parts[1]
            parts = parts[2:]
        if not parts or parts[0] not in _KINDS:
            return None
        plural = parts[0]
        # Cross-API guard: tfjobs only under the CRD prefix, core only core.
        if (plural == "tfjobs") != (prefix == TFJOB_API):
            return None
        name = parts[1] if len(parts) > 1 else None
        sub = parts[2] if len(parts) > 2 else None
        raw_tail = (q.get("tailLines") or ["0"])[0]
        try:
            tail = max(0, int(raw_tail))
        except ValueError:
            raise Invalid(f"invalid tailLines {raw_tail!r}")
        return _Route(plural, ns, name, sub,
                      (q.get("watch") or ["false"])[0] == "true",
                      _parse_selector(q), tail_lines=tail,
                      resource_version=(q.get("resourceVersion") or [None])[0])
    return None


class FakeAPIServer:
    """ThreadingHTTPServer over an ObjectStore; start() returns the URL."""

    def __init__(self, store: Optional[ObjectStore] = None, token: str = "",
                 port: int = 0, kubelet=None, registry=None, tracer=None,
                 latency_s: float = 0.0, bookmark_interval_s: float = 5.0,
                 write_qps: float = 0.0, write_burst: float = 0.0):
        self.store = store or ObjectStore()
        self.token = token
        self.port = port  # 0 = ephemeral
        # Injected per-request latency (seconds) on every API route —
        # loopback has none, a real API server has plenty (network RTT,
        # TLS, admission).  The wide-job bench uses this to measure the
        # RTT-dominated regime where serial plan execution pays
        # 2×replicas sequential round-trips (`bench.py --replicas --rtt-ms`).
        self.latency_s = latency_s
        # Optional node agent: enables the pod log subresource (the real
        # API server proxies /pods/{name}/log to the kubelet the same way).
        self.kubelet = kubelet
        # Observability surface: GET /metrics renders this registry in
        # Prometheus text exposition; GET /debug/traces dumps this tracer
        # as Chrome trace JSON.  Defaults (None) bind the process-global
        # obs registry/tracer, so in-process clusters expose controller +
        # workqueue + lifecycle + trainer series with zero wiring.
        self.registry = registry
        self.tracer = tracer
        # Periodic BOOKMARK cadence on idle watch streams: the RV
        # checkpoint that keeps a quiet (or namespace-filtered) client's
        # resume point fresh enough to survive a drop without a re-list.
        # ≤ 0 disables periodic bookmarks (the initial one is always sent).
        self.bookmark_interval_s = bookmark_interval_s
        # Bytes served by collection LISTs — what a reconnect storm of
        # re-listing informers costs in reply traffic (bench.py --churn
        # reports the delta across a storm).
        self._c_list_bytes = REGISTRY.counter(
            "kctpu_apiserver_list_bytes_total",
            "Response-body bytes served by collection LIST requests")
        # Per-tenant write-path isolation: each tenant gets its own token
        # bucket (write_qps tokens/s, write_burst deep; 0 = disabled), so
        # a submission storm from tenant A turns into A's own 429s + Retry-
        # After instead of queueing delay for every other tenant's writes.
        # The tenant is the TENANT_HEADER if present, else the route
        # namespace (the default tenant identity, api/tenant.py).
        self.write_qps = write_qps
        self.write_burst = write_burst if write_burst > 0 else max(
            1.0, 2.0 * write_qps)
        self._buckets: Dict[str, _TokenBucket] = {}
        self._buckets_lock = locks.named_lock("apiserver.buckets")
        self._c_throttled = REGISTRY.counter(
            "kctpu_apiserver_throttled_total",
            "Write requests rejected 429 by the per-tenant token bucket",
            ("tenant",))
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        # Live watch-stream watchers, so stop() can close every stream
        # deterministically (stop() wakes the handler's queue wait instead
        # of racing the 0.5 s poll) — restart-in-tests must not depend on
        # stream threads noticing the generation bump eventually.
        self._streams: set = set()
        self._streams_lock = locks.named_lock("apiserver.streams")
        # Watch-stream generation: drop_watches() bumps it and every live
        # stream closes at its next loop turn, forcing clients through
        # their reconnect path — a real API server does this on timeouts/
        # rolling restarts.  Clients holding a fresh RV resume; only a
        # 410-too-old resume degrades to the re-list (reflector gap) path.
        self._watch_gen = 0

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> str:
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            # Keep-alive idle deadline.  The pooled REST transport holds
            # persistent connections (urllib used to send Connection: close
            # per request, so this never mattered); without a timeout every
            # idle pooled socket would pin one server thread forever — and
            # outlive stop().  A timed-out connection closes server-side;
            # the client pool reconnects transparently on next checkout.
            timeout = 30
            # Response headers and bodies go out as separate writes; with
            # Nagle on, keep-alive round-trips eat 40 ms delayed-ACK
            # stalls (the client side sets TCP_NODELAY for the same
            # reason — see cluster/rest.py ConnectionPool.dial).
            disable_nagle_algorithm = True

            def log_message(self, *args):  # quiet
                pass

            def _deny(self) -> bool:
                if not outer.token:
                    return False
                auth = self.headers.get("Authorization", "")
                if auth == f"Bearer {outer.token}":
                    return False
                self._send(*_status(401, "Unauthorized", "bad token"))
                return True

            def _send(self, code: int, body: Any) -> int:
                data = json.dumps(body).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)
                return len(data)

            def _body(self) -> dict:
                return json.loads(self._raw_body or b"{}")

            def _dispatch(self, method: str) -> None:
                # Drain the request body up front: an early response (401,
                # 404) that leaves body bytes in the socket would corrupt
                # the next request on a keep-alive connection.
                n = int(self.headers.get("Content-Length", 0) or 0)
                self._raw_body = self.rfile.read(n) if n else b""
                if self._deny():
                    return
                if outer.latency_s > 0:
                    # time.sleep releases the GIL: concurrent requests pay
                    # the simulated RTT concurrently, as real wires do.
                    time.sleep(outer.latency_s)
                u = urlparse(self.path)
                if u.path == "/metrics" and method == "GET":
                    data = outer.render_metrics().encode()
                    self.send_response(200)
                    self.send_header(
                        "Content-Type",
                        "text/plain; version=0.0.4; charset=utf-8")
                    self.send_header("Content-Length", str(len(data)))
                    self.end_headers()
                    self.wfile.write(data)
                    return
                if u.path == "/debug/traces" and method == "GET":
                    self._send(200, outer.trace_dump())
                    return
                if u.path == "/debug/query" and method == "GET":
                    self._send(200, outer.debug_query(u.query))
                    return
                if u.path == "/debug/slos" and method == "GET":
                    self._send(200, outer.debug_slos())
                    return
                try:
                    r = _route(u.path, u.query)
                except APIError as e:
                    self._send(*_error_status(e))
                    return
                if r is None:
                    self._send(*_status(404, "NotFound", f"no route {u.path}"))
                    return
                if method in _WRITE_METHODS and outer.write_qps > 0:
                    tenant = (self.headers.get(TENANT_HEADER)
                              or r.namespace or "default")
                    retry_after = outer._throttle(tenant)
                    if retry_after > 0:
                        code, body = _status(
                            429, "TooManyRequests",
                            f"tenant {tenant!r} write budget exhausted")
                        data = json.dumps(body).encode()
                        self.send_response(code)
                        self.send_header("Content-Type", "application/json")
                        # Ceil to whole seconds but keep sub-second budgets
                        # honest: a 0 here would mean "retry immediately".
                        self.send_header("Retry-After",
                                         str(max(1, int(retry_after + 0.999))))
                        self.send_header("Content-Length", str(len(data)))
                        self.end_headers()
                        self.wfile.write(data)
                        return
                try:
                    outer._handle(self, method, r)
                except APIError as e:
                    self._send(*_error_status(e))
                except (BrokenPipeError, ConnectionResetError):
                    pass

            def do_GET(self):
                self._dispatch("GET")

            def do_POST(self):
                self._dispatch("POST")

            def do_PUT(self):
                self._dispatch("PUT")

            def do_DELETE(self):
                self._dispatch("DELETE")

            def do_PATCH(self):
                self._dispatch("PATCH")

        class Server(ThreadingHTTPServer):
            # Deep accept backlog: a reconnect storm of watchers (or a
            # wide-job create burst dialing fresh pool sockets) must queue
            # in the kernel, not get RSTs past the default backlog of 5.
            request_queue_size = 128

        self._httpd = Server(("127.0.0.1", self.port), Handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="fake-apiserver", daemon=True)
        self._thread.start()
        host, port = self._httpd.server_address
        return f"http://{host}:{port}"

    def stop(self) -> None:
        """Deterministic shutdown: close every live watch stream (each
        handler wakes on its watcher's stop sentinel and exits via the
        generation check — no 0.5 s poll race), stop the HTTP server,
        then flush the WAL so a test that restarts the server replays a
        byte-complete journal (no reliance on the torn-tail recovery
        path for a CLEAN exit)."""
        self._watch_gen += 1
        with self._streams_lock:
            streams = list(self._streams)
        for w in streams:
            w.stop()
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        self.store.flush_wal()

    def drop_watches(self) -> None:
        """Close every active watch stream (clients must reconnect and
        re-list).  Chaos/regression hook for the watch-gap path."""
        self._watch_gen += 1

    def _throttle(self, tenant: str) -> float:
        """Charge one write to ``tenant``'s bucket: 0.0 = admitted, else
        the Retry-After seconds.  Buckets materialize lazily per tenant
        (every tenant gets the same qps/burst: isolation, not quota —
        capacity policy lives in the scheduler's TenantQuota ledger)."""
        with self._buckets_lock:
            b = self._buckets.get(tenant)
            if b is None:
                b = self._buckets[tenant] = _TokenBucket(
                    self.write_qps, self.write_burst)
            wait = b.take()
        if wait > 0:
            self._c_throttled.labels(tenant).inc()
        return wait

    # -- observability surface -------------------------------------------------

    def render_metrics(self) -> str:
        """Prometheus text exposition of the bound (default: global) registry."""
        if self.registry is not None:
            return self.registry.render()
        from ..obs.metrics import REGISTRY

        return REGISTRY.render()

    def trace_dump(self) -> dict:
        """Chrome trace JSON of the bound (default: global) tracer."""
        if self.tracer is not None:
            return self.tracer.chrome_trace()
        from ..obs.trace import TRACER

        return TRACER.chrome_trace()

    def debug_query(self, query: str) -> dict:
        """Windowed TSDB queries (obs/tsdb.py) for ``kctpu query``:
        ?op=latest|range|rate|avg_over_time|quantile|series&name=...
        &labels={"k":"v"}&window=60&q=0.99 — always a JSON object, errors
        as {"error": ...}."""
        from urllib.parse import parse_qs

        from ..obs.tsdb import default_tsdb

        params = {k: v[0] for k, v in parse_qs(query or "").items()}
        return default_tsdb().query(params)

    def debug_slos(self) -> dict:
        """The SLO engine's objectives + live alert states (obs/slo.py)
        for ``kctpu alerts`` and the ``kctpu get`` banner."""
        from ..obs.slo import default_slo_engine

        return default_slo_engine().state()

    # -- request handling ------------------------------------------------------

    def _wire(self, plural: str, obj: Any) -> dict:
        _, api_version, kind = _KINDS[plural]
        d = serde.to_dict(obj)
        d["apiVersion"] = api_version
        d["kind"] = kind
        return d

    def _parse(self, plural: str, d: dict) -> Any:
        cls, _, _ = _KINDS[plural]
        return serde.from_dict(cls, d)

    def _handle(self, h, method: str, r: _Route) -> None:
        store = self.store
        fence = None
        raw_fence = h.headers.get(FENCE_HEADER)
        if raw_fence:
            try:
                fence = int(raw_fence)
            except ValueError:
                raise Invalid(f"invalid {FENCE_HEADER} {raw_fence!r}")
        if r.name is None:
            if method == "GET" and r.watch:
                self._stream_watch(h, r)
                return
            if method == "GET":
                # Snapshot LIST: immutable stored references, serialized
                # outside any store lock and never copied — handler threads
                # listing different kinds share no lock at all, so parallel
                # LISTs never queue on each other (true handler-level read
                # concurrency).
                items, rv = store.list_snapshot_with_rv(
                    r.plural, r.namespace, r.selector)
                _, api_version, kind = _KINDS[r.plural]
                self._c_list_bytes.inc(h._send(200, {
                    "apiVersion": api_version, "kind": kind + "List",
                    # ListMeta.resourceVersion: the watch resume point this
                    # snapshot is current through.
                    "metadata": {"resourceVersion": rv},
                    "items": [self._wire(r.plural, o) for o in items],
                }))
                return
            if method == "POST":
                obj = self._parse(r.plural, h._body())
                if r.namespace:
                    obj.metadata.namespace = r.namespace
                out = store.create(r.plural, obj, fence=fence)
                h._send(201, self._wire(r.plural, out))
                return
            raise NotFound(f"{method} not supported on collection")

        ns = r.namespace or "default"
        if method == "PUT" and r.plural == "pods" and r.subresource == "progress":
            from ..api.core import PodProgress

            progress = serde.from_dict(PodProgress, h._body())
            h._send(200, self._wire(
                r.plural, store.update_progress(r.plural, ns, r.name, progress,
                                fence=fence)))
            return
        if method == "GET" and r.plural == "pods" and r.subresource == "log":
            if self.kubelet is None:
                raise NotFound("no kubelet attached: pod logs unavailable")
            store.get_snapshot(r.plural, ns, r.name)  # 404 for unknown pods
            data = self.kubelet.logs(ns, r.name, tail_lines=r.tail_lines)
            h.send_response(200)
            h.send_header("Content-Type", "text/plain")
            h.send_header("Content-Length", str(len(data)))
            h.end_headers()
            h.wfile.write(data)
            return
        if method == "GET":
            # Snapshot read: serialize the immutable stored object directly,
            # no deep copy (the encode loop never mutates it).
            h._send(200, self._wire(
                r.plural, store.get_snapshot(r.plural, ns, r.name)))
            return
        if method == "PUT" and r.subresource == "status":
            obj = self._parse(r.plural, h._body())
            obj.metadata.namespace, obj.metadata.name = ns, r.name
            h._send(200, self._wire(
                r.plural, store.update_status(r.plural, obj, fence=fence)))
            return
        if method == "PUT":
            obj = self._parse(r.plural, h._body())
            obj.metadata.namespace, obj.metadata.name = ns, r.name
            h._send(200, self._wire(
                r.plural, store.update(r.plural, obj, fence=fence)))
            return
        if method == "PATCH":
            # Every PATCH body is one dialect: RFC 7386 merge, applied
            # server-side (maps merge per-key, null deletes, lists replace)
            # — metadata-only bodies included, so the REST client's
            # patch()/patch_meta() cannot diverge by code path.  The
            # status-subresource strip lives in store.patch, shared with
            # the in-process client.
            h._send(200, self._wire(
                r.plural, store.patch(r.plural, ns, r.name, h._body(),
                                      fence=fence)))
            return
        if method == "DELETE":
            store.delete(r.plural, ns, r.name, fence=fence)
            h._send(200, {"kind": "Status", "apiVersion": "v1",
                          "status": "Success", "code": 200})
            return
        raise NotFound(f"{method} not supported on item")

    def _stream_watch(self, h, r: _Route) -> None:
        """Chunked streaming of store watch events as JSON lines, until the
        client goes away.  ``?resourceVersion=`` resumes: buffered events
        after it replay first (store watch-cache; a too-old RV raised 410
        before we got here).  An initial BOOKMARK — and periodic ones while
        idle — carry the collection RV so every client always holds a fresh
        resume point; bookmarks travel through the watcher queue (enqueued
        under the store lock), so they can never overtake an event they
        claim to supersede.  Every exit path closes the connection: the
        stream ends without a terminating chunk, so a keep-alive client
        would otherwise block forever waiting for data that never comes
        (urllib's per-request Connection: close used to mask this; the
        pooled transport keeps sockets open)."""
        h.close_connection = True
        # auto_resume=False: if THIS stream's consumer is too slow and its
        # bounded queue overflows, the store drops the watcher and we close
        # the HTTP stream — the RV-resuming client reconnects and the watch
        # cache replays the overflow window (kube-apiserver behavior for a
        # watcher that can't keep up).
        w = self.store.watch(r.plural, r.namespace,
                             since_rv=r.resource_version, bookmark=True,
                             auto_resume=False)
        with self._streams_lock:
            self._streams.add(w)
        gen = self._watch_gen
        last_bookmark = time.monotonic()
        try:
            h.send_response(200)
            h.send_header("Content-Type", "application/json")
            h.send_header("Transfer-Encoding", "chunked")
            h.end_headers()

            def chunk(data: bytes) -> None:
                h.wfile.write(f"{len(data):x}\r\n".encode() + data + b"\r\n")
                h.wfile.flush()

            while True:
                ev = w.next(timeout=0.5)
                if self._watch_gen != gen:
                    break  # drop_watches(): end the stream mid-flight
                if w.dropped and ev is None:
                    break  # queue overflow: close now; client resumes by RV
                if ev is None:
                    if self._httpd is None:
                        break
                    if (self.bookmark_interval_s > 0
                            and time.monotonic() - last_bookmark
                            >= self.bookmark_interval_s):
                        last_bookmark = time.monotonic()
                        self.store.request_bookmark(w)  # arrives via the queue
                        continue
                    chunk(b"\n")  # keepalive; also detects dead clients
                    continue
                if ev.type == BOOKMARK:
                    last_bookmark = time.monotonic()
                    chunk(json.dumps({
                        "type": BOOKMARK,
                        "object": {"metadata": {"resourceVersion":
                                   ev.object.metadata.resource_version}},
                    }).encode() + b"\n")
                    continue
                # Encode once per EVENT, not per stream: the WatchEvent is
                # shared by every watcher queue and the watch cache (one
                # immutable snapshot), so the first stream to carry it pays
                # the JSON encode and caches the wire line for all others —
                # replays included.  The benign double-encode race under
                # concurrent first-carries produces identical bytes.
                line = ev.wire_line
                if line is None:
                    line = json.dumps({
                        "type": ev.type,
                        "object": self._wire(r.plural, ev.object),
                    }).encode() + b"\n"
                    ev.wire_line = line
                chunk(line)
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass
        finally:
            with self._streams_lock:
                self._streams.discard(w)
            w.stop()
