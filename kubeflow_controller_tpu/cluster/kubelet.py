"""Fake kubelet: drives pod phases the way a node agent would.

The reference validates controller behavior by watching real pods on a dev
cluster (ref: docs/design_doc.md:36-201); here the node side is simulated so
the whole loop runs in-process (SURVEY.md §4 "fake the platform boundary").

Two modes per pod:

- **simulated**: Pending -> Running -> Succeeded/Failed on a configurable
  policy clock.  PS replicas run forever, matching ``server.join()`` in the
  reference workload (ref: examples/workdir/mnist_replica.py:121-122).
- **executed**: the pod's first container command actually runs as a local
  subprocess (env injected from the container spec); the exit code decides
  the terminal phase.  This is how e2e tests run real JAX/MNIST workloads
  "in pods" with no cluster, honoring restartPolicy OnFailure with bounded
  restarts.

TPU pods gate on the :class:`TPUInventory` gang scheduler before leaving
Pending (all-or-nothing slice admission).
"""

from __future__ import annotations

import os
import subprocess
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, Optional, Set

from ..api.core import (
    PHASE_FAILED,
    PHASE_PENDING,
    PHASE_RUNNING,
    PHASE_SUCCEEDED,
    Pod,
)
from ..api.labels import LABEL_JOB_TYPE
from .client import Cluster
from .store import ADDED, DELETED, NotFound
from .tpu import TPUInventory, pod_requests_tpu


@dataclass
class PhasePolicy:
    """Clock for simulated pods."""

    pending_s: float = 0.0
    run_s: float = 0.02
    # Replica types that never reach a terminal phase on their own.
    run_forever_types: tuple = ("PS",)
    # Pod names to fail once (fault injection for recovery tests).
    fail_once: Set[str] = field(default_factory=set)

    def outcome(self, pod: Pod) -> Optional[str]:
        if pod.metadata.name in self.fail_once:
            self.fail_once.discard(pod.metadata.name)
            return PHASE_FAILED
        if pod.metadata.labels.get(LABEL_JOB_TYPE) in self.run_forever_types:
            return None  # runs forever
        return PHASE_SUCCEEDED


class FakeKubelet:
    def __init__(
        self,
        cluster: Cluster,
        policy: Optional[PhasePolicy] = None,
        inventory: Optional[TPUInventory] = None,
        execute: bool = False,
        max_restarts: int = 2,
    ):
        self.cluster = cluster
        self.policy = policy or PhasePolicy()
        self.inventory = inventory
        self.execute = execute
        self.max_restarts = max_restarts
        self._watcher = None
        self._threads: Dict[str, threading.Thread] = {}
        self._procs: Dict[str, subprocess.Popen] = {}
        self._stop = threading.Event()
        self._main: Optional[threading.Thread] = None

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        self._watcher = self.cluster.pods.watch()
        # Pick up pods created before the watch started.
        for pod in self.cluster.pods.list():
            self._spawn(pod)
        self._main = threading.Thread(target=self._run, name="fake-kubelet", daemon=True)
        self._main.start()

    def stop(self) -> None:
        self._stop.set()
        if self._watcher:
            self._watcher.stop()
        for proc in list(self._procs.values()):
            if proc.poll() is None:
                proc.terminate()

    def _run(self) -> None:
        while not self._stop.is_set():
            ev = self._watcher.next(timeout=0.2)
            if ev is None:
                continue
            if ev.type == ADDED:
                self._spawn(ev.object)
            elif ev.type == DELETED:
                proc = self._procs.get(self._key(ev.object))
                if proc is not None and proc.poll() is None:
                    proc.terminate()

    @staticmethod
    def _key(pod: Pod) -> str:
        return f"{pod.metadata.namespace}/{pod.metadata.name}"

    def _spawn(self, pod: Pod) -> None:
        key = self._key(pod)
        if key in self._threads:
            return
        t = threading.Thread(target=self._drive, args=(pod,), name=f"kubelet-{key}", daemon=True)
        self._threads[key] = t
        t.start()

    # -- phase driving -------------------------------------------------------

    def set_phase(self, namespace: str, name: str, phase: str, reason: str = "") -> None:
        """Directly transition a pod (also the manual hook for tests)."""
        try:
            pod = self.cluster.pods.get(namespace, name)
        except NotFound:
            return
        pod.status.phase = phase
        pod.status.reason = reason
        # The kubelet is the sole status writer for its pods: last-write-wins.
        pod.metadata.resource_version = ""
        try:
            self.cluster.store.update_status("pods", pod)
        except NotFound:
            pass

    def _drive(self, pod: Pod) -> None:
        ns, name = pod.metadata.namespace, pod.metadata.name
        # TPU pods wait in Pending for gang admission.
        if self.inventory is not None and pod_requests_tpu(pod):
            while not self._stop.is_set():
                if self.inventory.offer(pod):
                    break
                time.sleep(0.005)
                if self._gone(ns, name):
                    return
            if self._stop.is_set():
                return
        if self.policy.pending_s:
            time.sleep(self.policy.pending_s)
        if self._gone(ns, name):
            return
        self.set_phase(ns, name, PHASE_RUNNING)
        if self.execute and pod.spec.containers and (
            pod.spec.containers[0].command or pod.spec.containers[0].args
        ):
            self._execute(pod)
        else:
            self._simulate(pod)

    def _gone(self, ns: str, name: str) -> bool:
        try:
            p = self.cluster.pods.get(ns, name)
            return p.metadata.deletion_timestamp is not None
        except NotFound:
            return True

    def _simulate(self, pod: Pod) -> None:
        ns, name = pod.metadata.namespace, pod.metadata.name
        outcome = self.policy.outcome(pod)
        if outcome is None:
            return  # runs forever (PS)
        time.sleep(self.policy.run_s)
        if not self._gone(ns, name):
            self.set_phase(ns, name, outcome)

    def _execute(self, pod: Pod) -> None:
        ns, name = pod.metadata.namespace, pod.metadata.name
        c = pod.spec.containers[0]
        cmd = list(c.command) + list(c.args)
        env = dict(os.environ)
        env.update({e.name: e.value for e in c.env})
        restarts = 0
        while not self._stop.is_set():
            try:
                proc = subprocess.Popen(
                    cmd,
                    env=env,
                    cwd=c.working_dir or None,
                    stdout=subprocess.DEVNULL,
                    stderr=subprocess.PIPE,
                )
            except OSError as e:
                self.set_phase(ns, name, PHASE_FAILED, reason=f"StartError: {e}")
                return
            self._procs[self._key(pod)] = proc
            _, stderr = proc.communicate()
            if self._stop.is_set() or self._gone(ns, name):
                return
            if proc.returncode == 0:
                self.set_phase(ns, name, PHASE_SUCCEEDED)
                return
            if pod.spec.restart_policy in ("Always", "OnFailure") and restarts < self.max_restarts:
                restarts += 1
                continue
            tail = (stderr or b"")[-500:].decode(errors="replace")
            self.set_phase(ns, name, PHASE_FAILED, reason=f"Error: exit {proc.returncode}: {tail}")
            return
