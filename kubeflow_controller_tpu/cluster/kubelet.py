"""Fake kubelet: drives pod phases the way a node agent would.

The reference validates controller behavior by watching real pods on a dev
cluster (ref: docs/design_doc.md:36-201); here the node side is simulated so
the whole loop runs in-process (SURVEY.md §4 "fake the platform boundary").

Two modes per pod:

- **simulated**: Pending -> Running -> Succeeded/Failed on a configurable
  policy clock.  PS replicas run forever, matching ``server.join()`` in the
  reference workload (ref: examples/workdir/mnist_replica.py:121-122).
- **executed**: the pod's first container command actually runs as a local
  subprocess (env injected from the container spec); the exit code decides
  the terminal phase.  This is how e2e tests run real JAX/MNIST workloads
  "in pods" with no cluster, honoring restartPolicy OnFailure with bounded
  restarts.

TPU pods gate on the :class:`TPUInventory` gang scheduler before leaving
Pending (all-or-nothing slice admission).
"""

from __future__ import annotations

import os
import socket
import subprocess
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, Optional, Set

from ..api.core import (
    PHASE_FAILED,
    PHASE_PENDING,
    PHASE_RUNNING,
    PHASE_SUCCEEDED,
    Pod,
)
from ..api.labels import ANNOTATION_TRACE_CONTEXT, LABEL_JOB_TYPE
from ..obs import trace
from ..utils import locks
from .client import Cluster
from .store import ADDED, DELETED, MODIFIED, NotFound
from .tpu import TPUInventory, pod_requests_tpu


@dataclass
class PhasePolicy:
    """Clock for simulated pods."""

    pending_s: float = 0.0
    run_s: float = 0.02
    # Per-job run_s overrides (keyed by the tf_job_name label): lets one
    # bench run short foreground jobs against a long-running victim
    # (e.g. the elastic harvest probe) under one kubelet.
    run_s_by_job: Dict[str, float] = field(default_factory=dict)
    # Replica types that never reach a terminal phase on their own
    # (Serving replicas exit only through the drain protocol).
    run_forever_types: tuple = ("PS", "Serving")
    # Pod names to fail once (fault injection for recovery tests).
    fail_once: Set[str] = field(default_factory=set)
    # Simulated startup cost for TPU gang pods (the interpreter-import +
    # rendezvous analog the warm-pool zygote amortizes for executed pods):
    # the FIRST admission of a gang on this node pays ``cold_start_s``
    # extra Pending time; a READMISSION (preempted gang coming back) pays
    # only ``warm_start_s`` — its processes fork from the still-warm pool
    # and rejoin a known rendezvous.  Both 0 by default (no change for
    # tests that predate the capacity plane).
    cold_start_s: float = 0.0
    warm_start_s: float = 0.0
    # Simulated training-plane heartbeat interval: > 0 makes simulated
    # (non-PS) pods publish advancing PodProgress beats while Running —
    # the progress-plane analog of the phase clock.  0 = silent (default:
    # simulated pods predate the progress plane and most tests don't
    # want the extra status churn).
    heartbeat_s: float = 0.0

    def run_s_for(self, pod: Pod) -> float:
        return self.run_s_by_job.get(
            pod.metadata.labels.get("tf_job_name", ""), self.run_s)

    def outcome(self, pod: Pod) -> Optional[str]:
        if pod.metadata.name in self.fail_once:
            self.fail_once.discard(pod.metadata.name)
            return PHASE_FAILED
        if pod.metadata.labels.get(LABEL_JOB_TYPE) in self.run_forever_types:
            return None  # runs forever
        return PHASE_SUCCEEDED


class FakeKubelet:
    def __init__(
        self,
        cluster: Cluster,
        policy: Optional[PhasePolicy] = None,
        inventory: Optional[TPUInventory] = None,
        execute: bool = False,
        max_restarts: int = 2,
        warm_start: bool = True,
    ):
        self.cluster = cluster
        self.policy = policy or PhasePolicy()
        self.inventory = inventory
        self.execute = execute
        self.max_restarts = max_restarts
        # Warm-start: fork `python -m ...` pod commands from a pre-imported
        # zygote instead of cold-starting an interpreter per pod (the
        # image-pull-amortization analog; see zygote.py).
        self.warm_start = warm_start
        self._pool = None
        self._pool_lock = locks.named_lock("kubelet.pool")
        self._watcher = None
        self._threads: Dict[str, threading.Thread] = {}
        self._procs: Dict[str, subprocess.Popen] = {}
        # Fake cluster DNS: coordinator service hostname -> local port.
        self._svc_ports: Dict[str, int] = {}
        self._svc_lock = locks.named_lock("kubelet.svc-ports")
        self._warm: Dict[str, object] = {}
        # Pod keys whose failure was injected (fail_slice / preemption):
        # the drive loop must not restart them in place — the slice is
        # gone; replacement is the controller's job.
        self._injected_failures: Set[str] = set()
        # Serving drain protocol (docs/SERVING.md): pods whose drain
        # annotation we have acted on.  Executed pods get SIGTERM (their
        # serve loop closes intake, finishes in-flight and exits 0);
        # simulated pods are completed by the drive loop once their beats
        # show an empty queue and empty slots (or they never reported).
        self._draining: Set[str] = set()
        # Gangs that have run on this node before: their readmission is
        # warm (see PhasePolicy.cold_start_s/warm_start_s).
        self._warm_gangs: Set[str] = set()
        # Warm/cold pod-start telemetry (the warm-readmission evidence the
        # contention bench reports).
        from ..obs.metrics import REGISTRY

        self._c_starts = REGISTRY.counter(
            "kctpu_pod_starts_total",
            "Pod process starts by mode (warm = forked from the zygote / "
            "warm gang readmission; cold = fresh interpreter)", ("mode",))
        # A scheduler-shaped inventory (GangScheduler) needs us as the
        # eviction executor: preempted pods' processes are killed and the
        # pods marked Failed here, exactly like a slice failure.
        if inventory is not None and hasattr(inventory, "set_evictor"):
            inventory.set_evictor(self._evict_pods)
        # Pod log files (kubectl-logs analog): key -> list of file paths in
        # chronological order (one per restart / warm spawn).
        import tempfile

        self._log_dir = tempfile.mkdtemp(prefix="kubelet-logs-")
        self._log_paths: Dict[str, list] = {}
        # Progress file-drop directory (workloads/progress.py contract):
        # executed pods inherit it via env and drop heartbeat JSON here;
        # the main loop ingests drops into the pod progress subresource.
        self._progress_dir = tempfile.mkdtemp(prefix="kubelet-progress-")
        # Node-shared compile cache (workloads/compile_cache.py): executed
        # pods without a spec-pinned $KCTPU_COMPILE_CACHE share this dir,
        # so a replacement pod, a repeat job, or a warm-readmitted gang
        # forked from the zygote lands on the already-populated cache and
        # skips trace+XLA on its way to the first step — the compile-side
        # analog of the zygote's import amortization.  Lives as long as
        # the node agent, exactly like a real node's on-disk cache.
        self._compile_cache_dir = tempfile.mkdtemp(prefix="kubelet-jitcache-")
        # Rendezvous readiness file-drops (workloads/runtime.py): the
        # coordinator announces "about to bind" here so racing peers skip
        # the TCP poll window.
        self._rendezvous_dir = tempfile.mkdtemp(prefix="kubelet-rdv-")
        self._ingested_mtimes: Dict[str, float] = {}
        # Heartbeat kill switch (stall injection for tests/smoke): while
        # True, simulated beats stop publishing and file drops stop being
        # ingested — exactly what a hung training process looks like.
        self._hb_suspended = False
        self._stop = threading.Event()
        self._main: Optional[threading.Thread] = None

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        if self.execute and self.warm_start:
            self._prewarm()
        self._watcher = self.cluster.pods.watch()
        # Pick up pods created before the watch started.
        for pod in self.cluster.pods.list():
            self._spawn(pod)
        self._main = threading.Thread(target=self._run, name="fake-kubelet", daemon=True)
        self._main.start()

    def wait_warm(self, timeout: float = 60.0) -> bool:
        """Block until the zygote is ready (no-op without warm start)."""
        if self._pool is None:
            return True
        return self._pool._ready.wait(timeout=timeout)

    def _prewarm(self):
        """Create (once) and return the warm pool; start the zygote in the
        background so its framework preimport (the image-pull analog) is
        off every pod's critical path."""
        from .warmpool import WarmPool

        with self._pool_lock:
            if self._pool is None:
                repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
                    os.path.abspath(__file__))))
                self._pool = WarmPool(repo_root=repo_root)
                threading.Thread(target=self._pool.start, name="warmpool-prewarm",
                                 daemon=True).start()
            return self._pool

    def stop(self) -> None:
        import shutil

        self._stop.set()
        if self._watcher:
            self._watcher.stop()
        for proc in list(self._procs.values()):
            self._terminate_proc(proc)
        if self._pool is not None:
            self._pool.stop()
        shutil.rmtree(self._log_dir, ignore_errors=True)
        shutil.rmtree(self._progress_dir, ignore_errors=True)
        shutil.rmtree(self._compile_cache_dir, ignore_errors=True)
        shutil.rmtree(self._rendezvous_dir, ignore_errors=True)

    def logs(self, namespace: str, name: str, tail_lines: int = 0) -> bytes:
        """An executed pod's output — per run (across restarts) stdout then
        stderr, runs in chronological order; the kubectl-logs analog.  The
        two streams are separate files (stderr must stay unpolluted for
        failure reasons), so unlike a real container runtime they are NOT
        interleaved within a run.  Empty for simulated pods.

        ``tail_lines`` > 0 (the k8s ``tailLines`` param) returns only the
        last N lines, tail-reading files newest-first in bounded chunks
        (:meth:`_file_tail`) instead of shipping whole logs."""
        paths = self._log_paths.get(f"{namespace}/{name}", [])
        if tail_lines <= 0:
            out = b""
            for path in paths:
                try:
                    with open(path, "rb") as f:
                        out += f.read()
                except OSError:
                    pass
            return out
        collected: list = []
        for path in reversed(paths):
            need = tail_lines - len(collected)
            if need <= 0:
                break
            chunk = self._file_tail(path, limit=max(4096, need * 256))
            try:
                size = os.path.getsize(path)
            except OSError:
                size = len(chunk)
            lines = chunk.splitlines(keepends=True)
            if len(chunk) < size and lines:
                lines = lines[1:]  # first line may be torn mid-file
            collected = lines[-need:] + collected
        return b"".join(collected)

    # -- progress plane ------------------------------------------------------

    def suspend_heartbeats(self) -> None:
        """Stall injection: simulated beats stop publishing and executed
        pods' file drops stop being ingested — from the controller's view,
        training froze (the `make stall-smoke` hook)."""
        self._hb_suspended = True

    def resume_heartbeats(self) -> None:
        self._hb_suspended = False

    def _ingest_progress(self) -> None:
        """Apply new heartbeat file-drops to the pod progress subresource.
        mtime-deduplicated: each drop is re-applied only when the workload
        rewrote it (the reporter rewrites on every beat, so mtime IS the
        beat clock)."""
        from ..api.core import PodProgress
        from ..utils import serde
        from .store import APIError

        try:
            names = os.listdir(self._progress_dir)
        except OSError:
            return
        for fn in names:
            if not fn.endswith(".json") or "__" not in fn:
                continue
            path = os.path.join(self._progress_dir, fn)
            try:
                mtime = os.path.getmtime(path)
            except OSError:
                continue
            if self._ingested_mtimes.get(fn) == mtime:
                continue
            try:
                import json

                with open(path) as fh:
                    body = json.load(fh)
                progress = serde.from_dict(PodProgress, body)
            except (OSError, ValueError, TypeError):
                continue  # torn write: the next beat re-drops
            self._ingested_mtimes[fn] = mtime
            progress.timestamp = mtime  # beat time, even if ingestion lagged
            ns, _, pod_name = fn[: -len(".json")].partition("__")
            try:
                self.cluster.pods.update_progress(ns, pod_name, progress)
            except APIError:
                pass  # pod gone: the drop is cleaned with the pod

    def _new_log_file(self, key: str, suffix: str):
        """Create (and register) the next log file for a pod key."""
        import uuid

        safe = key.replace("/", "_")
        path = os.path.join(
            self._log_dir, f"{safe}-{uuid.uuid4().hex[:6]}.{suffix}")
        self._log_paths.setdefault(key, []).append(path)
        return open(path, "wb"), path

    @staticmethod
    def _file_tail(path: str, limit: int = 500) -> bytes:
        """Last ``limit`` bytes without reading the whole file."""
        try:
            with open(path, "rb") as f:
                f.seek(0, os.SEEK_END)
                size = f.tell()
                f.seek(max(0, size - limit))
                return f.read()
        except OSError:
            return b""

    def _drop_logs(self, key: str) -> None:
        """Forget (and delete) a pod's log files — called when the pod
        OBJECT is deleted, so logs of a kept terminal pod stay readable but
        a recreated same-name pod never serves its predecessor's output,
        and a long-lived kubelet does not grow unbounded."""
        for path in self._log_paths.pop(key, []):
            try:
                os.unlink(path)
            except OSError:
                pass

    def _drop_progress(self, pod: Pod) -> None:
        """Remove a deleted pod's heartbeat drop + dedup entry, so a
        recreated same-name pod never inherits its predecessor's beat."""
        from ..workloads.progress import drop_filename

        fn = drop_filename(pod.metadata.namespace, pod.metadata.name)
        self._ingested_mtimes.pop(fn, None)
        try:
            os.unlink(os.path.join(self._progress_dir, fn))
        except OSError:
            pass

    def _run(self) -> None:
        last_reap = time.monotonic()
        # Watch-gap recovery: the (now bounded) in-process watcher resumes
        # overflow drops transparently, but a 410-too-old resume is a real
        # gap — `gaps` bumps and anything in between is lost.  Re-list and
        # re-spawn (idempotent via self._threads); a pod DELETED during the
        # gap needs no handling here, its driver thread sees NotFound on
        # the next phase write and reaps itself.
        seen_gaps = getattr(self._watcher, "gaps", 0)
        while not self._stop.is_set():
            gaps = getattr(self._watcher, "gaps", 0)
            if gaps != seen_gaps:
                seen_gaps = gaps
                for pod in self.cluster.pods.list():
                    self._spawn(pod)
            # Node-side gang reaping: free slices whose gang has no live pod
            # left.  Required in two-process (REST) mode where the controller
            # holds no inventory handle; harmless redundancy otherwise.
            if self.inventory is not None and time.monotonic() - last_reap > 0.5:
                last_reap = time.monotonic()
                live = {
                    self._key(p) for p in self.cluster.pods.list()
                    if p.status.phase not in (PHASE_SUCCEEDED, PHASE_FAILED)
                    and p.metadata.deletion_timestamp is None
                }
                self.inventory.release_idle_gangs(live)
            if not self._hb_suspended:
                self._ingest_progress()
            self._check_draining()
            ev = self._watcher.next(timeout=0.2)
            if ev is None:
                continue
            if ev.type == ADDED:
                self._spawn(ev.object)
            elif ev.type == MODIFIED:
                self._maybe_drain(ev.object)
            elif ev.type == DELETED:
                key = self._key(ev.object)
                proc = self._procs.get(key)
                if proc is not None:
                    self._terminate_proc(proc)
                warm = self._warm.get(key)
                if warm is not None and self._pool is not None:
                    self._pool.kill(warm)
                self._drop_logs(key)
                self._drop_progress(ev.object)

    @staticmethod
    def _key(pod: Pod) -> str:
        return f"{pod.metadata.namespace}/{pod.metadata.name}"

    # -- serving drain -------------------------------------------------------

    def _maybe_drain(self, pod: Pod) -> None:
        """React (once) to a pod's drain annotation: SIGTERM the executed
        process — its serve loop stops intake, finishes in-flight
        requests and exits 0 (a LONG escalation grace: killing a draining
        server mid-request is exactly what the protocol exists to avoid)
        — or queue the simulated pod for beat-gated completion."""
        from ..api.labels import ANNOTATION_DRAIN

        if not pod.metadata.annotations.get(ANNOTATION_DRAIN):
            return
        key = self._key(pod)
        if key in self._draining:
            return
        if pod.status.phase in (PHASE_SUCCEEDED, PHASE_FAILED):
            return
        self._draining.add(key)
        proc = self._procs.get(key)
        if proc is not None:
            self._terminate_proc(proc, grace_s=30.0)
            return
        warm = self._warm.get(key)
        if warm is not None and warm.pid:
            import signal as _signal

            try:
                os.kill(warm.pid, _signal.SIGTERM)
            except OSError:
                pass

    def _check_draining(self) -> None:
        """Complete simulated draining pods whose beats ACKNOWLEDGE the
        drain (phase="drain") and show it finished (empty queue, empty
        batch) — or that never reported at all (pure-simulated pods have
        no intake to drain).  The acknowledgment is load-bearing: an idle
        pre-drain beat (queue 0, slots 0) must NOT complete the pod,
        because a request may be routed in the window before the replica
        notices its drain annotation and closes intake — completing on a
        stale idle beat would kill that request mid-flight.  A replica
        that wedges mid-drain keeps its heartbeat deadline (checker):
        the stall detector, not this loop, owns that failure mode."""
        for key in list(self._draining):
            ns, _, name = key.partition("/")
            try:
                pod = self.cluster.pods.get(ns, name)
            except NotFound:
                self._draining.discard(key)
                continue
            if pod.status.phase in (PHASE_SUCCEEDED, PHASE_FAILED):
                self._draining.discard(key)
                continue
            if key in self._procs or key in self._warm:
                continue  # executed: the process exits on its own
            pr = pod.status.progress
            if pr is None or (pr.phase == "drain" and pr.queue_depth == 0
                              and pr.slots_used == 0):
                self._draining.discard(key)
                self.set_phase(ns, name, PHASE_SUCCEEDED, reason="Drained")

    @staticmethod
    def _terminate_proc(proc, grace_s: float = 0.5) -> None:
        """Terminate a cold-started pod process with SIGKILL escalation:
        a multi-process jax.distributed worker ignores SIGTERM (XLA's
        coordination runtime installs its own handlers), and a HEALTHY
        gang torn down by an elastic re-shard would otherwise keep
        training as an orphan — writing checkpoints over the replacement
        generation's (the warm path escalates inside the zygote)."""
        if proc.poll() is not None:
            return
        proc.terminate()

        def _escalate(p=proc):
            if p.poll() is None:
                try:
                    p.kill()
                except OSError:
                    pass

        t = threading.Timer(grace_s, _escalate)
        t.name = "kubelet-kill-escalate"
        t.daemon = True
        t.start()

    def _spawn(self, pod: Pod) -> None:
        key = self._key(pod)
        if key in self._threads:
            return
        t = threading.Thread(target=self._drive_and_reap, args=(pod,),
                             name=f"kubelet-{key}", daemon=True)
        self._threads[key] = t
        t.start()

    def _drive_and_reap(self, pod: Pod) -> None:
        key = self._key(pod)
        try:
            self._drive(pod)
        finally:
            # A pod name never re-enters Running after its driver returns
            # (generateName makes replacements unique), so drop bookkeeping
            # rather than leak one entry per pod ever run.
            self._procs.pop(key, None)
            self._threads.pop(key, None)
            self._injected_failures.discard(key)

    # -- phase driving -------------------------------------------------------

    def set_phase(self, namespace: str, name: str, phase: str, reason: str = "") -> None:
        """Directly transition a pod (also the manual hook for tests)."""
        try:
            pod = self.cluster.pods.get(namespace, name)
        except NotFound:
            return
        pod.status.phase = phase
        pod.status.reason = reason
        # The kubelet is the sole status writer for its pods: last-write-wins.
        pod.metadata.resource_version = ""
        try:
            # Node agent, not a controller sync path: pod-status writes are
            # deliberately unfenced — kubelets outlive leader failovers.
            self.cluster.store.update_status("pods", pod)  # kctpu: vet-ok(fencing-token)
        except NotFound:
            pass

    def _stamp_start_mode(self, namespace: str, name: str, warm: bool) -> None:
        """Record warm/cold on the pod at spawn (best-effort) so the
        goodput ledger can attribute starting time to the right bucket."""
        from ..api.labels import (
            ANNOTATION_START_MODE, START_MODE_COLD, START_MODE_WARM)

        mode = START_MODE_WARM if warm else START_MODE_COLD

        def apply(meta):
            meta.annotations[ANNOTATION_START_MODE] = mode

        try:
            self.cluster.pods.patch_meta(namespace, name, apply)
        except NotFound:
            pass

    def _drive(self, pod: Pod) -> None:
        ns, name = pod.metadata.namespace, pod.metadata.name
        key = self._key(pod)
        # Node-agent leg of the causal trace: gate+start, attached to the
        # owning job's trace via the planner-stamped pod annotation.
        ctx = trace.TraceContext.decode(
            pod.metadata.annotations.get(ANNOTATION_TRACE_CONTEXT, ""))
        start = time.time()
        # TPU pods wait in Pending for gang admission.  With a scheduler
        # as the inventory, the wait is queue-ordered and the queue state
        # is published as the pod's Pending reason (so the controller and
        # CLI can surface "why is this job not running" in any process).
        if self.inventory is not None and pod_requests_tpu(pod):
            if not self._gate_tpu_pod(pod):
                return
            if key in self._injected_failures:
                # Preempted / slice-failed between admission and start:
                # the phase is already Failed, never run.
                self._injected_failures.discard(key)
                return
            started = getattr(self.inventory, "pod_started", None)
            if started is not None:
                started(pod)  # releases the gang's coordinator-first hold
            if not self._start_delay(pod):
                return
        if self.policy.pending_s:
            time.sleep(self.policy.pending_s)
        if self._gone(ns, name):
            return
        if key in self._injected_failures:
            self._injected_failures.discard(key)
            return
        self.set_phase(ns, name, PHASE_RUNNING)
        if ctx is not None:
            now = time.time()
            trace.add_span("kubelet/start", start, max(0.0, now - start),
                           ctx=ctx, pod=name, namespace=ns)
        if self.execute and pod.spec.containers and (
            pod.spec.containers[0].command or pod.spec.containers[0].args
        ):
            self._execute(pod)
        else:
            self._simulate(pod)

    def _gate_tpu_pod(self, pod: Pod) -> bool:
        """Poll the inventory/scheduler until the pod's gang is admitted.
        Returns False when the pod went away (or we are stopping).  While
        queued, the scheduler's queue position is mirrored into the
        Pending pod's status.reason (rate-limited to changes)."""
        from ..api.labels import ANNOTATION_GANG_NAME

        ns, name = pod.metadata.namespace, pod.metadata.name
        gang = pod.metadata.annotations.get(ANNOTATION_GANG_NAME, "")
        queue_info = getattr(self.inventory, "queue_info", None)
        last_reason = ""
        ticks = 0
        while not self._stop.is_set():
            if self.inventory.offer(pod):
                return True
            ticks += 1
            if queue_info is not None and gang and ticks % 10 == 1:
                reason = queue_info(gang)
                if reason and reason != last_reason:
                    last_reason = reason
                    self.set_phase(ns, name, PHASE_PENDING, reason=reason)
            time.sleep(0.005)
            if self._gone(ns, name):
                return False
        return False

    def _start_delay(self, pod: Pod) -> bool:
        """Simulated warm/cold start cost for admitted TPU gang pods (the
        zygote/import analog; executed pods pay their real costs instead).
        Returns False when the pod vanished mid-delay."""
        from ..api.labels import ANNOTATION_GANG_NAME

        if self.execute and pod.spec.containers and (
            pod.spec.containers[0].command or pod.spec.containers[0].args
        ):
            return True  # real process: real costs, counted at spawn time
        ns, name = pod.metadata.namespace, pod.metadata.name
        gang = pod.metadata.annotations.get(ANNOTATION_GANG_NAME, "") or self._key(pod)
        warm = gang in self._warm_gangs
        self._c_starts.labels("warm" if warm else "cold").inc()
        self._stamp_start_mode(ns, name, warm)
        delay = self.policy.warm_start_s if warm else self.policy.cold_start_s
        deadline = time.monotonic() + delay
        while delay > 0 and not self._stop.is_set():
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            time.sleep(min(0.02, remaining))
            if self._gone(ns, name) or self._key(pod) in self._injected_failures:
                return False
        self._warm_gangs.add(gang)
        return not self._stop.is_set()

    def _evict_pods(self, pod_keys, reason: str) -> None:
        """Preemption executor (registered with the gang scheduler): kill
        the victim gang's processes and mark its pods Failed with a reason
        naming the preemptor — the same flow a slice failure takes, so the
        controller's whole-gang replacement handles readmission."""
        keys = set(pod_keys)
        for pod in self.cluster.pods.list():
            key = self._key(pod)
            if key not in keys:
                continue
            self._injected_failures.add(key)
            proc = self._procs.get(key)
            if proc is not None:
                self._terminate_proc(proc)
            warm = self._warm.get(key)
            if warm is not None and self._pool is not None:
                self._pool.kill(warm)
            self.set_phase(pod.metadata.namespace, pod.metadata.name,
                           PHASE_FAILED, reason=reason)

    @staticmethod
    def _is_gang_member(env: Dict[str, str]) -> bool:
        """A multi-process jax.distributed member must NEVER restart in
        place: its world died (or it is the one that died) and a rejoined
        process would hang against the torn collective.  The pod fails
        instead, and the controller's recovery plane replaces the whole
        gang under a fresh generation."""
        from ..planner.materialize import ENV_NUM_PROCESSES

        try:
            return int(env.get(ENV_NUM_PROCESSES, "1") or "1") > 1
        except ValueError:
            return False

    def chaos_kill(self, namespace: str, name: str) -> Optional[str]:
        """Chaos-plane fault injection (recovery/chaos.py): kill one pod the
        way its runtime mode dies for real — SIGKILL the executed process
        (cold subprocess or warm zygote fork), else flip the simulated pod
        to Failed through the injected-failure path slice failures use.
        Returns the mode used ("process" | "warm" | "simulated") or None
        when there was nothing to kill."""
        import signal as _signal

        key = f"{namespace}/{name}"
        proc = self._procs.get(key)
        if proc is not None and proc.poll() is None:
            try:
                proc.send_signal(_signal.SIGKILL)
                return "process"
            except OSError:
                return None
        warm = self._warm.get(key)
        if warm is not None:
            if warm.pid:
                try:
                    os.kill(warm.pid, _signal.SIGKILL)
                    return "warm"
                except OSError:
                    pass
            if self._pool is not None:
                self._pool.kill(warm)
                return "warm"
            return None
        try:
            pod = self.cluster.pods.get(namespace, name)
        except NotFound:
            return None
        if pod.status.phase in (PHASE_PENDING, PHASE_RUNNING):
            # Simulated pod: same flow as a slice failure — suppress the
            # in-place outcome and let the controller replace it.
            self._injected_failures.add(key)
            self.set_phase(namespace, name, PHASE_FAILED,
                           reason="ChaosKill: injected fault")
            return "simulated"
        return None

    def fail_slice(self, slice_name: str, reason: str = "SliceFailed") -> list:
        """Inject a whole-slice failure — the TPU failure domain (SURVEY §5):
        every pod of the gang bound to the slice has its process killed and
        is marked Failed.  In-place restart is suppressed (the hardware is
        gone); index-preserving gang replacement is the controller's job.
        Returns the failed pod names."""
        if self.inventory is None:
            return []
        keys = set(self.inventory.fail_slice(slice_name))
        failed = []
        for pod in self.cluster.pods.list():
            key = self._key(pod)
            if key not in keys:
                continue
            self._injected_failures.add(key)
            proc = self._procs.get(key)
            if proc is not None:
                self._terminate_proc(proc)
            warm = self._warm.get(key)
            if warm is not None and self._pool is not None:
                self._pool.kill(warm)
            self.set_phase(pod.metadata.namespace, pod.metadata.name,
                           PHASE_FAILED, reason=reason)
            failed.append(pod.metadata.name)
        return failed

    def _gone(self, ns: str, name: str) -> bool:
        try:
            p = self.cluster.pods.get(ns, name)
            return p.metadata.deletion_timestamp is not None
        except NotFound:
            return True

    def _simulate(self, pod: Pod) -> None:
        ns, name = pod.metadata.namespace, pod.metadata.name
        outcome = self.policy.outcome(pod)
        if outcome is None:
            return  # runs forever (PS)
        run_s = self.policy.run_s_for(pod)
        hb = self.policy.heartbeat_s
        if hb > 0:
            # "Training": publish an advancing step every heartbeat tick
            # for the whole simulated run (suspend_heartbeats silences the
            # publishing, not the clock — a stall, not a pause).
            deadline = time.monotonic() + run_s
            step = 0
            while not self._stop.is_set():
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                time.sleep(min(hb, remaining))
                if self._gone(ns, name):
                    return
                step += 1
                if not self._hb_suspended:
                    self._publish_sim_beat(ns, name, step, hb)
        else:
            time.sleep(run_s)
        if self._key(pod) in self._injected_failures:
            self._injected_failures.discard(self._key(pod))
            return  # fail_slice already marked the pod Failed
        if not self._gone(ns, name):
            self.set_phase(ns, name, outcome)

    def _publish_sim_beat(self, ns: str, name: str, step: int,
                          interval_s: float) -> None:
        from ..api.core import PodProgress
        from .store import APIError

        try:
            self.cluster.pods.update_progress(ns, name, PodProgress(
                step=step,
                examples_per_sec=round(100.0 / interval_s, 3),
                loss=round(1.0 / step, 4),
                phase="fit",
            ))
        except APIError:
            pass  # pod deleted mid-beat

    def _resolve_coordinator(self, env: Dict[str, str]) -> None:
        """Fake cluster DNS for the jax.distributed coordinator.

        The materializer wires coordinator addresses as service DNS names
        (resolvable by real cluster DNS, not on this host).  Map each
        distinct coordinator hostname to a stable free localhost port so
        every pod of a gang rendezvouses at the same 127.0.0.1 address —
        the same indirection kube-dns provides, collapsed to one machine.

        The mapping is keyed by (hostname, gang generation, gang width): a
        replacement gang (recovery plane) gets a FRESH port, so its
        coordinator can never race the dead generation's not-yet-released
        socket — the fake-DNS analog of the generation-keyed readiness
        drops.  Width rides the key too (elastic plane): every re-shard
        bumps the generation anyway, but a width mismatch must never
        rendezvous against another width's coordinator even if a
        generation is somehow reused.
        """
        from ..planner.materialize import (
            ENV_COORDINATOR,
            ENV_GANG_GENERATION,
            ENV_GANG_WIDTH,
        )

        addr = env.get(ENV_COORDINATOR, "")
        if not addr or ":" not in addr:
            return
        host = addr.rsplit(":", 1)[0]
        if host in ("localhost", "127.0.0.1"):
            return
        try:
            socket.inet_aton(host)
            return  # already an IP literal
        except OSError:
            pass
        dns_key = (f"{host}#g{env.get(ENV_GANG_GENERATION, '0') or '0'}"
                   f"w{env.get(ENV_GANG_WIDTH, '') or '-'}")
        with self._svc_lock:
            port = self._svc_ports.get(dns_key)
        if port is None:
            # Bind the probe socket OUTSIDE the lock (socket I/O under
            # _svc_lock stalled every concurrently-starting pod; caught by
            # `kctpu vet` lock-blocking-call).  First registration wins:
            # a gang racing here must agree on ONE port per dns_key.
            s = socket.socket()
            s.bind(("127.0.0.1", 0))
            candidate = s.getsockname()[1]
            s.close()
            with self._svc_lock:
                port = self._svc_ports.setdefault(dns_key, candidate)
        env[ENV_COORDINATOR] = f"127.0.0.1:{port}"

    def _wire_progress_env(self, pod: Pod, env: Dict[str, str]) -> None:
        """Downward-API analog for the heartbeat contract: tell the
        workload process who it is and where beats go (the kubelet's
        file-drop dir, ingested by the main loop).  A template-provided
        transport (e.g. a REST URL for two-process runs) wins."""
        from ..workloads.progress import (
            ENV_POD_NAME,
            ENV_POD_NAMESPACE,
            ENV_PROGRESS_DIR,
            ENV_PROGRESS_URL,
        )

        env[ENV_POD_NAMESPACE] = pod.metadata.namespace or "default"
        env[ENV_POD_NAME] = pod.metadata.name
        if not env.get(ENV_PROGRESS_URL):
            env.setdefault(ENV_PROGRESS_DIR, self._progress_dir)

    def _wire_startup_env(self, env: Dict[str, str]) -> None:
        """Time-to-first-step plumbing: the node-shared persistent compile
        cache (a spec-pinned $KCTPU_COMPILE_CACHE — planner _dir_env —
        wins; env.update ran before this) and the rendezvous readiness
        drop dir."""
        from ..planner.materialize import ENV_COMPILE_CACHE
        from ..workloads.runtime import ENV_RENDEZVOUS_DIR

        env.setdefault(ENV_COMPILE_CACHE, self._compile_cache_dir)
        env.setdefault(ENV_RENDEZVOUS_DIR, self._rendezvous_dir)

    def _execute(self, pod: Pod) -> None:
        from .warmpool import python_module_argv

        ns, name = pod.metadata.namespace, pod.metadata.name
        c = pod.spec.containers[0]
        cmd = list(c.command) + list(c.args)
        env = dict(os.environ)
        env.update({e.name: e.value for e in c.env})
        self._resolve_coordinator(env)
        self._wire_progress_env(pod, env)
        self._wire_startup_env(env)
        if self.warm_start:
            argv = python_module_argv(cmd)
            if argv is not None:
                self._execute_warm(pod, argv, env)
                return
        restarts = 0
        while not self._stop.is_set():
            if self._key(pod) in self._injected_failures:
                self._injected_failures.discard(self._key(pod))
                return  # slice failed before/between spawns; stay Failed
            # Output goes to FILES (the pod's logs, kubectl-logs analog),
            # never pipes: a concurrent fork elsewhere in this thread-heavy
            # process (the warm-pool zygote master forks without exec) can
            # inherit a pipe's write end in the window before Popen closes
            # it, and a long-lived holder means communicate() never sees
            # EOF — the pod would hang Running forever after its process
            # exited.  Files have no EOF wait.  stdout/stderr are separate
            # files (same layout as the warm pool): block-buffered stdout
            # in a combined file could displace the traceback out of the
            # failure-reason tail.
            outf, _ = self._new_log_file(self._key(pod), "out")
            errf, err_path = self._new_log_file(self._key(pod), "err")
            try:
                try:
                    proc = subprocess.Popen(
                        cmd,
                        env=env,
                        cwd=c.working_dir or None,
                        stdout=outf,
                        stderr=errf,
                    )
                except OSError as e:
                    self.set_phase(ns, name, PHASE_FAILED, reason=f"StartError: {e}")
                    return
                self._c_starts.labels("cold").inc()
                if restarts == 0:
                    self._stamp_start_mode(ns, name, warm=False)
                self._procs[self._key(pod)] = proc
                proc.wait()
            finally:
                outf.close()
                errf.close()
            if self._stop.is_set() or self._gone(ns, name):
                return
            if self._key(pod) in self._injected_failures:
                self._injected_failures.discard(self._key(pod))
                return  # phase already Failed by fail_slice; no restart
            if proc.returncode == 0:
                self.set_phase(ns, name, PHASE_SUCCEEDED)
                return
            if (pod.spec.restart_policy in ("Always", "OnFailure")
                    and restarts < self.max_restarts
                    and not self._is_gang_member(env)):
                # Gang members never restart in place (torn collective);
                # the recovery plane replaces the whole gang instead.
                restarts += 1
                continue
            self.set_phase(ns, name, PHASE_FAILED,
                           reason=self._exit_reason(proc.returncode, err_path))
            return

    def _exit_reason(self, code: int, err_path: str = "",
                     tail: bytes = b"") -> str:
        """Failure reason for a nonzero exit: the gang guard's cooperative
        tear-down code gets a first-class reason (it is a *detection*, not
        a crash — kctpu describe should say so), everything else keeps the
        stderr-tail shape tests and operators rely on."""
        from ..recovery.rendezvous import EXIT_REJOIN

        if code == EXIT_REJOIN:
            return ("GangBroken: peer loss detected (exit "
                    f"{EXIT_REJOIN}); awaiting gang replacement")
        if not tail and err_path:
            tail = self._file_tail(err_path)
        return f"Error: exit {code}: {tail.decode(errors='replace')}"

    def _execute_warm(self, pod: Pod, argv, env) -> None:
        """Fork the pod process from the warm zygote (see zygote.py)."""
        ns, name = pod.metadata.namespace, pod.metadata.name
        key = self._key(pod)
        pool = self._prewarm()
        c = pod.spec.containers[0]
        restarts = 0
        try:
            while not self._stop.is_set():
                if key in self._injected_failures:
                    self._injected_failures.discard(key)
                    return  # slice failed before/between spawns; stay Failed
                try:
                    proc = pool.spawn(argv, env, c.working_dir, key)
                except OSError as e:
                    self.set_phase(ns, name, PHASE_FAILED, reason=f"StartError: {e}")
                    return
                self._c_starts.labels("warm").inc()
                if restarts == 0:
                    self._stamp_start_mode(ns, name, warm=True)
                self._warm[key] = proc
                # Register the pool's files as this pod's logs.
                self._log_paths.setdefault(key, []).extend(
                    [proc.stdout_path, proc.stderr_path])
                code = proc.wait(poll_stop=lambda: self._stop.is_set() or self._gone(ns, name))
                if code is None or self._stop.is_set() or self._gone(ns, name):
                    pool.kill(proc)
                    return
                if key in self._injected_failures:
                    self._injected_failures.discard(key)
                    return  # phase already Failed by fail_slice; no restart
                if code == 0:
                    self.set_phase(ns, name, PHASE_SUCCEEDED)
                    return
                if (pod.spec.restart_policy in ("Always", "OnFailure")
                        and restarts < self.max_restarts
                        and not self._is_gang_member(env)):
                    restarts += 1
                    continue
                self.set_phase(ns, name, PHASE_FAILED,
                               reason=self._exit_reason(
                                   code, tail=proc.stderr_tail()))
                return
        finally:
            self._warm.pop(key, None)
