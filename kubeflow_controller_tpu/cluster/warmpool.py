"""Kubelet-side client for the warm-start zygote (see zygote.py)."""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import threading
from dataclasses import dataclass, field
from typing import Dict, Optional


@dataclass
class WarmProc:
    """Handle for one pod process forked from the zygote."""

    req_id: int
    pid: int = 0
    exit_code: Optional[int] = None
    stdout_path: str = ""
    stderr_path: str = ""
    _done: threading.Event = field(default_factory=threading.Event)

    def wait(self, poll_stop=None) -> Optional[int]:
        """Block until exit; ``poll_stop()`` True aborts the wait."""
        while not self._done.wait(timeout=0.1):
            if poll_stop is not None and poll_stop():
                return None
        return self.exit_code

    def stderr_tail(self, limit: int = 500) -> bytes:
        try:
            with open(self.stderr_path, "rb") as f:
                data = f.read()
            return data[-limit:]
        except OSError:
            return b""


class WarmPool:
    """Owns the zygote subprocess; thread-safe spawn/kill."""

    def __init__(self, repo_root: Optional[str] = None):
        self._lock = threading.Lock()
        self._next_id = 0
        self._procs: Dict[int, WarmProc] = {}
        self._zygote: Optional[subprocess.Popen] = None
        self._reader: Optional[threading.Thread] = None
        self._tmpdir = tempfile.mkdtemp(prefix="warmpool-")
        self._repo_root = repo_root
        self._ready = threading.Event()

    def start(self) -> None:
        with self._lock:
            if self._zygote is not None:
                return
            env = dict(os.environ)
            if self._repo_root:
                env["PYTHONPATH"] = self._repo_root + os.pathsep + env.get("PYTHONPATH", "")
            self._zygote = subprocess.Popen(
                [sys.executable, "-m", "kubeflow_controller_tpu.cluster.zygote"],
                stdin=subprocess.PIPE,
                stdout=subprocess.PIPE,
                stderr=subprocess.DEVNULL,
                env=env,
                cwd=self._repo_root or None,
            )
            self._reader = threading.Thread(
                target=self._read_loop, name="warmpool-reader", daemon=True
            )
            self._reader.start()
        self._ready.wait(timeout=60)

    def _read_loop(self) -> None:
        z = self._zygote
        for raw in z.stdout:
            try:
                msg = json.loads(raw)
            except ValueError:
                continue
            if msg.get("event") == "ready":
                self._ready.set()
                continue
            proc = self._procs.get(msg.get("id"))
            if proc is None:
                continue
            if msg["event"] == "started":
                proc.pid = msg["pid"]
            elif msg["event"] == "exit":
                proc.exit_code = msg["code"]
                with self._lock:
                    self._procs.pop(proc.req_id, None)
                proc._done.set()
        # zygote died: fail everything outstanding and allow a restart
        with self._lock:
            self._zygote = None
            outstanding = list(self._procs.values())
            self._procs.clear()
            self._ready.clear()
        for proc in outstanding:
            if proc.exit_code is None:
                proc.exit_code = -1
                proc._done.set()

    def spawn(self, argv, env, cwd, key: str) -> WarmProc:
        """argv: the command AFTER the interpreter, e.g. ["-m", "mod", ...].

        Raises OSError if the zygote is (or just went) unreachable; callers
        surface that as a pod StartError."""
        self.start()
        with self._lock:
            if self._zygote is None or self._zygote.poll() is not None:
                raise OSError("warm-start zygote is not running")
            self._next_id += 1
            rid = self._next_id
            safe = key.replace("/", "_")
            proc = WarmProc(
                req_id=rid,
                stdout_path=os.path.join(self._tmpdir, f"{safe}-{rid}.out"),
                stderr_path=os.path.join(self._tmpdir, f"{safe}-{rid}.err"),
            )
            self._procs[rid] = proc
            req = {
                "id": rid,
                "argv": list(argv),
                "env": dict(env),
                "cwd": cwd or "",
                "stdout": proc.stdout_path,
                "stderr": proc.stderr_path,
            }
            try:
                self._zygote.stdin.write((json.dumps(req) + "\n").encode())
                self._zygote.stdin.flush()
            except (BrokenPipeError, OSError) as e:
                self._procs.pop(rid, None)
                raise OSError(f"warm-start zygote unreachable: {e}") from e
        return proc

    def kill(self, proc: WarmProc) -> None:
        with self._lock:
            if self._zygote is None or proc.exit_code is not None:
                return
            try:
                self._zygote.stdin.write(
                    (json.dumps({"kill": proc.req_id}) + "\n").encode())
                self._zygote.stdin.flush()
            except (BrokenPipeError, ValueError):
                pass

    def stop(self) -> None:
        with self._lock:
            z, self._zygote = self._zygote, None
        if z is not None:
            try:
                z.stdin.close()  # zygote sees EOF, kills children, exits
                z.wait(timeout=5)
            except (OSError, subprocess.TimeoutExpired):
                z.terminate()
        import shutil

        shutil.rmtree(self._tmpdir, ignore_errors=True)


def python_module_argv(command) -> Optional[list]:
    """If the pod command is `<python> -m module args...` (or starts with
    "-m"), return the argv after the interpreter; else None (not warmable)."""
    cmd = list(command)
    if not cmd:
        return None
    if cmd[0] == "-m":
        return cmd
    base = os.path.basename(cmd[0])
    if base.startswith("python") and len(cmd) >= 3 and cmd[1] == "-m":
        return cmd[1:]
    return None
