"""Kubelet-side client for the warm-start zygote (see zygote.py)."""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import threading
from dataclasses import dataclass, field
from typing import Dict, Optional

from ..utils import locks


@dataclass
class WarmProc:
    """Handle for one pod process forked from the zygote."""

    req_id: int
    pid: int = 0
    exit_code: Optional[int] = None
    stdout_path: str = ""
    stderr_path: str = ""
    _done: threading.Event = field(default_factory=threading.Event)

    def wait(self, poll_stop=None) -> Optional[int]:
        """Block until exit; ``poll_stop()`` True aborts the wait."""
        while not self._done.wait(timeout=0.1):
            if poll_stop is not None and poll_stop():
                return None
        return self.exit_code

    def stderr_tail(self, limit: int = 500) -> bytes:
        try:
            with open(self.stderr_path, "rb") as f:
                data = f.read()
            return data[-limit:]
        except OSError:
            return b""


class WarmPool:
    """Owns the zygote subprocess; thread-safe spawn/kill."""

    def __init__(self, repo_root: Optional[str] = None):
        self._lock = locks.named_lock("warmpool.state")
        # Serializes writes on the zygote's stdin pipe — its whole purpose
        # is holding across I/O, so it is declared to the analysis plane.
        self._io_lock = locks.named_lock("warmpool.stdin", allow_blocking=True)
        self._next_id = 0
        self._procs: Dict[int, WarmProc] = {}
        self._zygote: Optional[subprocess.Popen] = None
        self._spawning = False
        self._reader: Optional[threading.Thread] = None
        self._tmpdir = tempfile.mkdtemp(prefix="warmpool-")
        self._repo_root = repo_root
        self._ready = threading.Event()

    def start(self) -> None:
        with self._lock:
            if self._zygote is not None or self._spawning:
                spawn_here = False
            else:
                self._spawning = True
                spawn_here = True
        if spawn_here:
            # The zygote fork/exec runs OUTSIDE the state lock (a
            # subprocess spawn under _lock blocked every concurrent
            # spawn()/kill(); caught by `kctpu vet` lock-blocking-call).
            # _spawning keeps racing starters parked on _ready instead.
            env = dict(os.environ)
            if self._repo_root:
                env["PYTHONPATH"] = self._repo_root + os.pathsep + env.get("PYTHONPATH", "")
            try:
                z = subprocess.Popen(
                    [sys.executable, "-m", "kubeflow_controller_tpu.cluster.zygote"],
                    stdin=subprocess.PIPE,
                    stdout=subprocess.PIPE,
                    stderr=subprocess.DEVNULL,
                    env=env,
                    cwd=self._repo_root or None,
                )
                reader = threading.Thread(
                    target=self._read_loop, args=(z,), name="warmpool-reader",
                    daemon=True)
            except BaseException:
                with self._lock:
                    self._spawning = False
                raise
            with self._lock:
                self._zygote = z
                self._reader = reader
                self._spawning = False
            reader.start()
        self._ready.wait(timeout=60)

    def _read_loop(self, z: subprocess.Popen) -> None:
        for raw in z.stdout:
            try:
                msg = json.loads(raw)
            except ValueError:
                continue
            if msg.get("event") == "ready":
                self._ready.set()
                continue
            proc = self._procs.get(msg.get("id"))
            if proc is None:
                continue
            if msg["event"] == "started":
                proc.pid = msg["pid"]
            elif msg["event"] == "exit":
                proc.exit_code = msg["code"]
                with self._lock:
                    self._procs.pop(proc.req_id, None)
                proc._done.set()
        # zygote died: fail everything outstanding and allow a restart
        with self._lock:
            if self._zygote is z:
                self._zygote = None
            outstanding = list(self._procs.values())
            self._procs.clear()
            self._ready.clear()
        for proc in outstanding:
            if proc.exit_code is None:
                proc.exit_code = -1
                proc._done.set()

    def spawn(self, argv, env, cwd, key: str) -> WarmProc:
        """argv: the command AFTER the interpreter, e.g. ["-m", "mod", ...].

        Raises OSError if the zygote is (or just went) unreachable; callers
        surface that as a pod StartError."""
        self.start()
        with self._lock:
            z = self._zygote
            if z is None or z.poll() is not None:
                raise OSError("warm-start zygote is not running")
            self._next_id += 1
            rid = self._next_id
            safe = key.replace("/", "_")
            proc = WarmProc(
                req_id=rid,
                stdout_path=os.path.join(self._tmpdir, f"{safe}-{rid}.out"),
                stderr_path=os.path.join(self._tmpdir, f"{safe}-{rid}.err"),
            )
            self._procs[rid] = proc
        req = {
            "id": rid,
            "argv": list(argv),
            "env": dict(env),
            "cwd": cwd or "",
            "stdout": proc.stdout_path,
            "stderr": proc.stderr_path,
        }
        try:
            # Pipe writes go through the dedicated stdin lock, not the
            # state lock: request framing stays atomic without parking
            # state readers behind pipe I/O.
            with self._io_lock:
                z.stdin.write((json.dumps(req) + "\n").encode())
                z.stdin.flush()
        except (BrokenPipeError, ValueError, OSError) as e:
            with self._lock:
                self._procs.pop(rid, None)
            raise OSError(f"warm-start zygote unreachable: {e}") from e
        return proc

    def kill(self, proc: WarmProc) -> None:
        with self._lock:
            z = self._zygote
        if z is None or proc.exit_code is not None:
            return
        try:
            with self._io_lock:
                z.stdin.write(
                    (json.dumps({"kill": proc.req_id}) + "\n").encode())
                z.stdin.flush()
        except (BrokenPipeError, ValueError, OSError):
            pass

    def stop(self) -> None:
        with self._lock:
            z, self._zygote = self._zygote, None
        if z is not None:
            try:
                z.stdin.close()  # zygote sees EOF, kills children, exits
                z.wait(timeout=5)
            except (OSError, subprocess.TimeoutExpired):
                z.terminate()
        import shutil

        shutil.rmtree(self._tmpdir, ignore_errors=True)


def python_module_argv(command) -> Optional[list]:
    """If the pod command is `<python> -m module args...` (or starts with
    "-m"), return the argv after the interpreter; else None (not warmable)."""
    cmd = list(command)
    if not cmd:
        return None
    if cmd[0] == "-m":
        return cmd
    base = os.path.basename(cmd[0])
    if base.startswith("python") and len(cmd) >= 3 and cmd[1] == "-m":
        return cmd[1:]
    return None
