"""In-memory object store with k8s API-server semantics.

Reproduces the behaviors the reference's controller correctness depends on
(SURVEY.md §7 "hard parts"):

- monotonically increasing resourceVersions, bumped on every write;
- optimistic concurrency: update with a stale resourceVersion -> Conflict
  (the reference does full-object Update with no retry at
  pkg/controller/controller.go:643-649; our controller layers retry on top);
- ``generateName`` materialization (base + 5 random alphanumerics, ref:
  vendor/k8s.io/kubernetes/pkg/api/v1/generate.go:48-72);
- watch streams that deliver ADDED/MODIFIED/DELETED in write order, each
  carrying the immutable stored snapshot shared read-only by all watchers
  (watchers can never mutate the store; see ``_notify``);
- a bounded per-kind **watch cache** of recent ``(rv, event)`` pairs (the
  kube-apiserver watch cache): ``watch(kind, since_rv=...)`` replays the
  buffered events after ``since_rv`` before going live, so a client that
  lost its stream resumes from its last-seen resourceVersion instead of
  re-listing the collection; a resume point older than the buffer raises
  :class:`TooOldResourceVersion` (HTTP 410 Gone over REST), and
  ``list_with_rv`` hands out the collection RV so every LIST is a resume
  point;
- deletionTimestamp + cascading garbage collection of controller-owned
  objects (net-new: the reference's delete handlers are stubs,
  pkg/controller/controller.go:522-524, 601-603).

Concurrency model (the PR-6 shard rebuild — kube-apiserver watch cache /
Maple-style control-plane partitioning, PAPERS.md):

- **Per-kind shards.**  Each kind owns its own lock, collection map, watch
  cache ring, and watcher list, so readers and writers of one kind never
  contend with another kind's.  resourceVersions and uids come from one
  process-wide atomic counter, so cross-kind ordering (and every PR-5
  resume invariant) is preserved: within a kind, RV order == write order
  (the shard lock serializes same-kind writers); across kinds RVs are
  globally unique and monotonic.
- **Write-time snapshots, copy-outside-the-lock reads.**  Every write
  swaps a freshly-copied object into the collection and NEVER mutates a
  stored object in place — stored objects are immutable snapshots.  Reads
  therefore grab references under the shard lock and deep-copy after
  releasing it (or skip the copy entirely on the wire path:
  ``get_snapshot`` / ``list_snapshot_with_rv`` hand out read-only
  references for serialization).  ``_notify`` shares the stored snapshot
  itself with every watcher and the watch cache — zero copies on the
  fan-out, and the API server caches ONE wire encoding per event.
- **Bounded watcher queues.**  A slow consumer overflows into a
  dropped-stream close: in-process watchers transparently re-subscribe
  from their last delivered RV (exactly-once replay; a 410-too-old bumps
  ``gaps`` so cache consumers re-list), while API-server streams
  (``auto_resume=False``) close so the RV-resuming REST client reconnects
  through the PR-5 replay path.
- ``ObjectStore(sharded=False)`` is the pre-shard baseline — one global
  lock shared by every shard, reads copied *inside* the lock with the
  ``copy.deepcopy`` copier — kept so ``bench.py --store-contention
  --no-shard`` measures exactly what this rebuild removed.

Lock-wait time is measured per shard on every acquisition
(``kctpu_store_lock_wait_seconds``; :meth:`ObjectStore.lock_wait_stats`).
"""

from __future__ import annotations

import bisect
import collections
import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..api.meta import ObjectMeta, get_controller_of, matches_selector
from ..obs.metrics import REGISTRY, Family, Sample, bucket_quantile
from ..utils import locks, serde
from ..utils.names import generate_name


class APIError(Exception):
    pass


class NotFound(APIError):
    pass


class AlreadyExists(APIError):
    pass


class Conflict(APIError):
    """Stale resourceVersion on update (optimistic-concurrency failure)."""


class FencingError(Conflict):
    """Write carried a fencing token (lease generation) below the current
    leader generation: the writer was deposed and its in-flight updates
    are rejected (docs/HA.md "Fencing").  A Conflict subclass so every
    existing retry/abort path treats a fenced-off write like a lost CAS —
    which, semantically, it is."""


class Invalid(APIError):
    pass


class TooOldResourceVersion(APIError):
    """The requested resume resourceVersion has fallen out of the bounded
    watch cache (HTTP 410 Gone over REST): the client must re-list."""


# Watch event types (ref: watch.Added/Modified/Deleted in apimachinery;
# BOOKMARK per watch.Bookmark — an RV checkpoint carrying no object change).
ADDED = "ADDED"
MODIFIED = "MODIFIED"
DELETED = "DELETED"
BOOKMARK = "BOOKMARK"

# The coordination kind (ha/lease.py).  Lease writes are exempt from the
# fence check — the lease IS the fencing authority, so gating it on itself
# would wedge every election — and instead RAISE the floor: a stored lease
# with a higher generation deposes every older token.
LEASES_KIND = "leases"


@dataclass
class WatchEvent:
    type: str
    object: Any  # the immutable stored snapshot — shared, treat as read-only
    # One wire encoding per event, computed lazily by the API server and
    # shared by every stream that carries this event (replay included):
    # the "encode once, N watchers" half of the snapshot fan-out.
    wire_line: Optional[bytes] = field(default=None, repr=False, compare=False)


@dataclass
class Bookmark:
    """BOOKMARK event payload: only ``metadata.resource_version`` is
    meaningful — the RV through which the carrying stream is complete."""

    metadata: ObjectMeta


def _bookmark_event(rv: str) -> WatchEvent:
    return WatchEvent(BOOKMARK, Bookmark(metadata=ObjectMeta(resource_version=rv)))


def _uid_seq(uid: str) -> int:
    """The sequence component of a store-issued ``uid-N`` (0 for foreign
    uids) — how recovery restores the uid counter from replayed objects."""
    if uid.startswith("uid-"):
        try:
            return int(uid[4:])
        except ValueError:
            return 0
    return 0


# Lock-wait histogram bucket upper bounds (seconds).  Uncontended acquires
# land in the first bucket; the tail is sized for GIL-preemption convoys
# (a holder descheduled mid-critical-section parks waiters for multiple
# 5 ms GIL quanta).
LOCK_WAIT_BUCKETS = (1e-5, 5e-5, 1e-4, 5e-4, 1e-3, 5e-3,
                     1e-2, 5e-2, 0.1, 0.5, 1.0)


class _Shard:
    """One kind's slice of the store: lock + collection + watch plane +
    lock-wait accounting.  Used as a context manager: ``with shard:`` is a
    *timed* acquisition — contended waits are bucketed per shard (mutated
    only while the lock is held, so no extra synchronization)."""

    __slots__ = ("kind", "lock", "objects", "watchers", "watch_cache",
                 "evicted_rv", "wait_counts", "wait_sum", "wait_max",
                 "contended", "overflows", "owners")

    def __init__(self, kind: str, lock: "locks.NamedRLock"):
        self.kind = kind
        self.lock = lock
        self.objects: Dict[tuple, Any] = {}
        # Controller-owner index: owner uid -> keys of owned objects.
        # Maintained at the _notify choke point (every write passes it,
        # under this shard's lock) so cascading GC resolves an owner's
        # children by lookup instead of scanning every object of every
        # kind — at 10k jobs / 50k pods the full scan made EACH delete
        # O(cluster) and terminal cleanup quadratic.  Postings are
        # re-verified against the live object at cascade time, so a stale
        # entry (owner ref changed by adoption/release) can never delete
        # a re-owned child — it is just discarded.
        self.owners: Dict[str, set] = {}
        self.watchers: List["Watcher"] = []
        self.watch_cache: "collections.deque[Tuple[int, WatchEvent]]" = (
            collections.deque())
        # Newest rv ever evicted from the ring: resume points at or below
        # it are detected exactly as 410-too-old.
        self.evicted_rv = 0
        self.wait_counts = [0] * (len(LOCK_WAIT_BUCKETS) + 1)
        self.wait_sum = 0.0
        self.wait_max = 0.0
        self.contended = 0
        self.overflows = 0

    def __enter__(self) -> "_Shard":
        if self.lock.acquire(blocking=False):
            self.wait_counts[0] += 1
            return self
        t0 = time.perf_counter()
        self.lock.acquire()
        waited = time.perf_counter() - t0
        self.contended += 1
        self.wait_sum += waited
        if waited > self.wait_max:
            self.wait_max = waited
        self.wait_counts[bisect.bisect_left(LOCK_WAIT_BUCKETS, waited)] += 1
        return self

    def __exit__(self, *exc) -> None:
        self.lock.release()


class _EventQueue:
    """One watch stream's event pipe: a deque under a named condition,
    replacing ``queue.Queue`` on the store fan-out hot path.

    Two scale properties ``queue.Queue`` lacks:

    - **coalesced wakeups**: ``put`` (called by every writer, under the
      shard lock, once per watcher per event) only notifies when a
      consumer is actually parked in ``get``/``get_batch``.  Under load
      the consumer is draining, never parked, so the fan-out costs one
      deque append per watcher — no condition signalling at all.
    - **batch drain**: ``get_batch`` hands the consumer everything
      buffered in ONE lock acquisition.  An informer behind a 50k-pod
      phase storm pays one lock round-trip per *batch* instead of per
      event.

    Protocol-compatible with the slice of ``queue.Queue`` the watch plane
    uses: ``put``, ``get(timeout=...)`` raising ``queue.Empty``, and
    ``qsize`` (racily exact — the only writer holds the shard lock, and
    the overflow check tolerates a pop-in-flight undercount of one)."""

    __slots__ = ("_cond", "_dq", "_waiters")

    def __init__(self):
        self._cond = locks.named_condition("store.watchq")
        self._dq: "collections.deque" = collections.deque()
        self._waiters = 0

    def put(self, item) -> None:
        with self._cond:
            self._dq.append(item)
            if self._waiters:
                self._cond.notify()

    def qsize(self) -> int:
        return len(self._dq)

    def get(self, timeout: Optional[float] = None):
        batch = self.get_batch(1, timeout=timeout)
        if not batch:
            raise queue.Empty
        return batch[0]

    def get_batch(self, max_n: int, timeout: Optional[float] = None) -> list:
        """Up to ``max_n`` buffered items; blocks up to ``timeout`` for the
        first one (None = wait forever), never for the rest."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while not self._dq:
                remaining = (None if deadline is None
                             else deadline - time.monotonic())
                if remaining is not None and remaining <= 0:
                    return []
                self._waiters += 1
                try:
                    self._cond.wait(timeout=remaining)
                finally:
                    self._waiters -= 1
            n = min(max_n, len(self._dq))
            return [self._dq.popleft() for _ in range(n)]


class Watcher:
    """One watch stream: a **bounded** queue of :class:`WatchEvent`.

    A slow consumer that lets the queue hit ``max_queue`` is dropped by
    the write path (writers never block on watchers): buffered events
    still drain in order, then the stream ends.  With ``auto_resume``
    (in-process consumers) the next :meth:`next` transparently
    re-subscribes from the last delivered RV — the watch cache replays the
    overflow window exactly once; only a 410-too-old bumps :attr:`gaps`,
    sending cache consumers through their re-list fallback.  API-server
    streams pass ``auto_resume=False`` and surface :attr:`dropped` so the
    HTTP stream closes and the remote client drives its own RV resume."""

    def __init__(self, store: "ObjectStore", kind: str, namespace: Optional[str],
                 max_queue: int = 0, auto_resume: bool = True):
        self._store = store
        self.kind = kind
        self.namespace = namespace
        self.max_queue = max_queue  # 0 = unbounded
        self.auto_resume = auto_resume
        self.queue = _EventQueue()
        #: Reconnects that could NOT resume (events lost): consumers
        #: holding a cache must full re-list, as after a REST 410.
        self.gaps = 0
        self._last_rv = 0  # newest RV the consumer has fully observed
        self._dropped = False
        self._stopped = False

    @property
    def dropped(self) -> bool:
        """True once the write path evicted this watcher for overflowing
        its queue (buffered events still drain)."""
        return self._dropped

    def next(self, timeout: Optional[float] = None) -> Optional[WatchEvent]:
        """Blocking pop; None on stop or timeout."""
        try:
            ev = self.queue.get(timeout=timeout)
        except queue.Empty:
            return None
        if ev is None:
            # End-of-stream sentinel: a stop() is final; an overflow drop
            # re-subscribes in place when auto_resume is on.
            if self._dropped and not self._stopped and self.auto_resume:
                self._store._resubscribe(self)
                return self.next(timeout=0)
            return None
        rv = ev.object.metadata.resource_version
        if rv:
            self._last_rv = int(rv)
        return ev

    def next_batch(self, max_n: int = 256,
                   timeout: Optional[float] = None) -> List[WatchEvent]:
        """Up to ``max_n`` events in one queue drain (the fan-out batching
        consumers use under load): blocks up to ``timeout`` for the first
        event only.  Overflow-drop sentinels resubscribe in place exactly
        as :meth:`next`; a stop sentinel ends the batch early.  Returns
        an empty list on timeout or a stopped stream."""
        out: List[WatchEvent] = []
        first_timeout = timeout
        while len(out) < max_n:
            batch = self.queue.get_batch(max_n - len(out),
                                         timeout=first_timeout)
            first_timeout = 0  # only the first pop may block
            if not batch:
                break
            for ev in batch:
                if ev is None:
                    if (self._dropped and not self._stopped
                            and self.auto_resume):
                        # Re-subscribe; the replayed window is now buffered
                        # and the outer loop picks it up without blocking.
                        self._store._resubscribe(self)
                    else:
                        return out  # stream over
                    continue
                rv = ev.object.metadata.resource_version
                if rv:
                    self._last_rv = int(rv)
                out.append(ev)
        return out

    def stop(self) -> None:
        if not self._stopped:
            self._stopped = True
            self._store._remove_watcher(self)
            self.queue.put(None)  # sentinel to unblock consumers


class ObjectStore:
    """The in-memory API server. Collections are keyed by plural kind
    ("tfjobs", "pods", "services"); objects by (namespace, name).

    ``sharded=False`` is the global-lock, copy-under-the-lock baseline
    (the pre-shard store) for ``bench.py --store-contention --no-shard``.
    """

    def __init__(self, watch_cache_size: int = 1024, sharded: bool = True,
                 watch_queue_size: int = 8192, wal=None):
        self._sharded = sharded
        # Durability (ha/wal.py): with a WriteAheadLog attached, every
        # write's (rv, event, kind, snapshot) is journaled — fsync'd under
        # the WAL lock — before the write returns.  recover() rebuilds an
        # RV-identical store (shards + watch caches) from it.
        self._wal = wal
        # Fencing floor: the highest lease generation ever stored through
        # this store (see LEASES_KIND above).  Writes carrying an older
        # fence token raise FencingError; unfenced writes (fence=None —
        # node agents, workloads, tests) are never gated.  Plain int:
        # mutated only under the leases shard lock, read racily elsewhere
        # (a momentarily stale floor only delays a rejection by one write,
        # it can never un-depose a leader — the floor is monotonic).
        self._fence_floor = 0
        self._c_fence_rejected = REGISTRY.counter(
            "kctpu_ha_fencing_rejections_total",
            "Store writes rejected because their fencing token (lease "
            "generation) was below the current leader generation")
        # With snapshot reads off (baseline), every read copies inside the
        # lock with the slow copier — the exact pre-PR-6 cost profile.
        self._snapshot = sharded
        self._copy = serde.deep_copy if sharded else serde.slow_deep_copy
        self._shards: Dict[str, _Shard] = {}
        self._shards_guard = locks.named_lock("store.shards-guard")
        # Baseline mode: one RLock shared by every shard.
        self._global_lock = None if sharded else locks.named_rlock("store.global")
        # Process-wide RV/uid counter: one tiny lock, never held while any
        # shard lock is being acquired (shard -> meta is the only nesting
        # order, so shards cannot deadlock through it).
        self._meta_lock = locks.named_lock("store.meta")
        self._rv = 0
        self._uid = 0
        self._watch_cache_size = watch_cache_size
        self._watch_queue_size = watch_queue_size
        self._recorder = None  # opt-in history hook; see attach_recorder
        self._c_replayed = REGISTRY.counter(
            "kctpu_watch_replayed_events_total",
            "Watch events replayed from the server watch cache on "
            "RV-resumed watch connects")
        self._g_cache_depth = REGISTRY.gauge(
            "kctpu_watch_cache_depth",
            "Buffered (rv, event) pairs in the per-kind server watch cache",
            ("kind",))
        # Shard-local families (lock-wait histogram, shard depth, watch
        # queue depth/overflows) render at scrape time from the shard
        # counters — zero hot-path cost beyond the ints themselves.
        REGISTRY.register_collector("store", self._collect_families)

    # -- internals -----------------------------------------------------------

    def _shard(self, kind: str) -> _Shard:
        sh = self._shards.get(kind)
        if sh is None:
            with self._shards_guard:
                sh = self._shards.get(kind)
                if sh is None:
                    sh = _Shard(kind, self._global_lock
                                or locks.named_rlock(f"store.shard:{kind}"))
                    # Scrape-time depth callback: updating the gauge from
                    # _notify would re-serialize every shard's writers on
                    # the one instrument lock — the exact cross-kind
                    # convoy the shards exist to remove.
                    self._g_cache_depth.labels(kind).set_function(
                        lambda sh=sh: len(sh.watch_cache))
                    self._shards[kind] = sh
        return sh

    @property
    def _watch_cache(self) -> Dict[str, "collections.deque[Tuple[int, WatchEvent]]"]:
        """Compat view (tests/debugging): kind -> watch-cache ring."""
        return {k: sh.watch_cache for k, sh in self._shards.items()}

    def _next_rv(self) -> str:
        with self._meta_lock:
            self._rv += 1
            return str(self._rv)

    def _next_uid(self) -> str:
        with self._meta_lock:
            self._uid += 1
            return f"uid-{self._uid}"

    def _notify(self, sh: _Shard, ev_type: str, obj: Any) -> None:
        # Zero-copy fan-out: the stored object IS an immutable snapshot
        # (every write swaps in a fresh copy), so the event shares it with
        # every watcher's queue AND the per-kind watch cache — the
        # apiserver analog: one object, one (lazily cached) encode, N
        # streams.  Watch consumers treat event objects as read-only
        # (informers hand out copies on the mutating read paths).  The
        # cache entry is appended even with zero live watchers: a
        # disconnected client's resume depends on exactly the events it
        # wasn't there to see.  Caller holds the shard lock.
        if not self._snapshot:
            obj = serde.slow_deep_copy(obj)  # baseline: per-event copy
        self._index_owner(sh, obj, removed=(ev_type == DELETED))
        if self._wal is not None:
            # Journal-before-visible: the record hits the fsync'd log
            # before any watcher (or the caller) can observe the write.
            # Per-kind append order == RV order because this runs under
            # the shard lock; cross-kind interleaving in the file is
            # harmless (replay is keyed by kind).
            self._wal.append(int(obj.metadata.resource_version), ev_type,
                             sh.kind, obj)
        ev = WatchEvent(ev_type, obj)
        buf = sh.watch_cache
        buf.append((int(obj.metadata.resource_version), ev))
        if len(buf) > self._watch_cache_size:
            evicted_rv, _ = buf.popleft()
            if evicted_rv > sh.evicted_rv:
                sh.evicted_rv = evicted_rv
        dropped = None
        for w in sh.watchers:
            if w.namespace is not None and w.namespace != obj.metadata.namespace:
                continue
            if w.max_queue and w.queue.qsize() >= w.max_queue:
                # Slow consumer: drop the stream instead of blocking the
                # writer or growing without bound.  The sentinel lands
                # AFTER the buffered prefix, so everything already queued
                # still drains in order; the overflow window replays from
                # the watch cache on reconnect.
                w._dropped = True
                sh.overflows += 1
                w.queue.put(None)
                dropped = (dropped or []) + [w]
                continue
            w.queue.put(ev)
        if dropped:
            sh.watchers = [w for w in sh.watchers if w not in dropped]

    @staticmethod
    def _index_owner(sh: _Shard, obj: Any, removed: bool = False) -> None:
        """Maintain the shard's owner-uid posting for one write (caller
        holds the shard lock)."""
        ref = get_controller_of(obj.metadata)
        if ref is None or not ref.uid:
            return
        key = (obj.metadata.namespace, obj.metadata.name)
        if removed:
            posting = sh.owners.get(ref.uid)
            if posting is not None:
                posting.discard(key)
                if not posting:
                    del sh.owners[ref.uid]
        else:
            sh.owners.setdefault(ref.uid, set()).add(key)

    def _remove_watcher(self, w: Watcher) -> None:
        sh = self._shard(w.kind)
        with sh:
            if w in sh.watchers:
                sh.watchers.remove(w)

    def _resubscribe(self, w: Watcher) -> None:
        """Re-attach an overflow-dropped in-process watcher: replay every
        buffered event after its last delivered RV (exactly once, in
        order), or bump ``gaps`` when the window was evicted (the
        in-process 410), then go live."""
        sh = self._shard(w.kind)
        with sh:
            if w._stopped:
                return
            if w._last_rv < sh.evicted_rv:
                w.gaps += 1  # events lost for good: consumer must re-list
            else:
                replayed = 0
                for rv, ev in sh.watch_cache:
                    if rv <= w._last_rv:
                        continue
                    if (w.namespace is not None
                            and ev.object.metadata.namespace != w.namespace):
                        continue
                    w.queue.put(ev)
                    replayed += 1
                if replayed:
                    self._c_replayed.inc(replayed)
            w._dropped = False
            sh.watchers.append(w)

    # -- HA: fencing ---------------------------------------------------------

    @property
    def fence_floor(self) -> int:
        """Current leader generation: the fence every leader write must
        meet or beat (docs/HA.md)."""
        return self._fence_floor

    def _check_fence(self, kind: str, fence: Optional[int]) -> None:
        """Reject a write whose fencing token predates the current leader
        generation.  Runs under the target shard lock, before any
        mutation.  ``fence=None`` = unfenced writer (kubelet, workloads,
        tests): never gated — fencing exists to stop DEPOSED leaders, not
        non-leaders."""
        if fence is None or kind == LEASES_KIND:
            return
        if fence < self._fence_floor:
            self._c_fence_rejected.inc()
            raise FencingError(
                f"{kind}: fencing token {fence} < leader generation "
                f"{self._fence_floor}: writer was deposed")

    def _maybe_raise_fence(self, kind: str, obj: Any) -> None:
        """A stored lease with a higher generation deposes older tokens.
        Caller holds the leases shard lock, so floor updates serialize."""
        if kind != LEASES_KIND:
            return
        gen = int(getattr(getattr(obj, "spec", None), "generation", 0) or 0)
        if gen > self._fence_floor:
            self._fence_floor = gen

    # -- API surface ---------------------------------------------------------

    def create(self, kind: str, obj: Any,
               fence: Optional[int] = None) -> Any:
        # The incoming object is copied BEFORE the lock (the store must
        # own its snapshot; the caller keeps mutating theirs), stamped and
        # inserted under it, and the caller-owned return copy is made
        # after release.
        obj = self._copy(obj)
        meta: ObjectMeta = obj.metadata
        sh = self._shard(kind)
        with sh:
            self._check_fence(kind, fence)
            if not meta.name:
                if not meta.generate_name:
                    raise Invalid("either name or generateName is required")
                # Retry on (unlikely) suffix collision, as the apiserver does.
                for _ in range(8):
                    candidate = generate_name(meta.generate_name)
                    if (meta.namespace, candidate) not in sh.objects:
                        meta.name = candidate
                        break
                else:
                    raise AlreadyExists(f"could not generate unique name for {meta.generate_name}")
            key = (meta.namespace, meta.name)
            if key in sh.objects:
                raise AlreadyExists(f"{kind} {key} already exists")
            meta.uid = self._next_uid()
            meta.resource_version = self._next_rv()
            meta.creation_timestamp = time.time()
            sh.objects[key] = obj
            self._maybe_raise_fence(kind, obj)
            self._notify(sh, ADDED, obj)
            if not self._snapshot:
                return serde.slow_deep_copy(obj)
        return self._copy(obj)

    def get(self, kind: str, namespace: str, name: str) -> Any:
        """A quorum/live read — this is what the adoption path's
        ``canAdoptFunc`` uses to re-check UIDs (ref: pkg/controller/
        helper.go:137-146, RecheckDeletionTimestamp at
        controller_ref_manager.go:373-385)."""
        sh = self._shard(kind)
        with sh:
            obj = sh.objects.get((namespace, name))
            if obj is None:
                raise NotFound(f"{kind} {namespace}/{name} not found")
            if not self._snapshot:
                return serde.slow_deep_copy(obj)
        # Snapshot mode: the stored object can never mutate — copy it for
        # the caller AFTER releasing the shard lock.
        return self._copy(obj)

    def get_snapshot(self, kind: str, namespace: str, name: str) -> Any:
        """The wire-serialization read: returns the immutable stored
        snapshot itself, **no copy** — the caller (the API server's encode
        path) must treat it as read-only.  Falls back to the copying
        :meth:`get` in baseline mode."""
        if not self._snapshot:
            return self.get(kind, namespace, name)
        sh = self._shard(kind)
        with sh:
            obj = sh.objects.get((namespace, name))
            if obj is None:
                raise NotFound(f"{kind} {namespace}/{name} not found")
            return obj

    def _select(self, sh: _Shard, namespace: Optional[str],
                selector: Optional[Dict[str, str]]) -> List[Any]:
        """Matching stored references; caller holds the shard lock."""
        out = []
        for (ns, _), obj in sh.objects.items():
            if namespace is not None and ns != namespace:
                continue
            if selector is not None and not matches_selector(obj.metadata.labels, selector):
                continue
            out.append(obj)
        return out

    def list(
        self,
        kind: str,
        namespace: Optional[str] = None,
        selector: Optional[Dict[str, str]] = None,
    ) -> List[Any]:
        return self.list_with_rv(kind, namespace, selector)[0]

    def list_with_rv(
        self,
        kind: str,
        namespace: Optional[str] = None,
        selector: Optional[Dict[str, str]] = None,
    ) -> Tuple[List[Any], str]:
        """list() plus the collection resourceVersion (ListMeta.resourceVersion
        on a real API server): the resume point a client hands back as
        ``watch(since_rv=...)`` so a stream can start exactly where the
        LIST's snapshot ends — no gap, no re-list.

        Snapshot and RV come from ONE shard-lock acquisition: no same-kind
        write can interleave between them, so the RV can never drift ahead
        of (or behind) the snapshot.  Writes to OTHER kinds may bump the
        global counter concurrently — harmless: they hold no events of
        this kind, so resuming this kind from the returned RV still replays
        exactly what the snapshot is missing."""
        sh = self._shard(kind)
        with sh:
            refs = self._select(sh, namespace, selector)
            rv = str(self._rv)
            if not self._snapshot:
                return [serde.slow_deep_copy(o) for o in refs], rv
        return [self._copy(o) for o in refs], rv

    def list_snapshot_with_rv(
        self,
        kind: str,
        namespace: Optional[str] = None,
        selector: Optional[Dict[str, str]] = None,
    ) -> Tuple[List[Any], str]:
        """The wire-serialization LIST: immutable stored snapshots,
        **no copies** — read-only for the caller's encode loop.  Same
        single-acquisition RV contract as :meth:`list_with_rv`."""
        if not self._snapshot:
            return self.list_with_rv(kind, namespace, selector)
        sh = self._shard(kind)
        with sh:
            return self._select(sh, namespace, selector), str(self._rv)

    def update(self, kind: str, obj: Any,
               fence: Optional[int] = None) -> Any:
        obj = self._copy(obj)
        meta: ObjectMeta = obj.metadata
        key = (meta.namespace, meta.name)
        sh = self._shard(kind)
        finalized = None
        with sh:
            self._check_fence(kind, fence)
            existing = sh.objects.get(key)
            if existing is None:
                raise NotFound(f"{kind} {key} not found")
            if meta.resource_version and meta.resource_version != existing.metadata.resource_version:
                raise Conflict(
                    f"{kind} {key}: resourceVersion {meta.resource_version} "
                    f"!= {existing.metadata.resource_version}"
                )
            # uid, creation and deletion timestamps are immutable via update.
            obj.metadata.uid = existing.metadata.uid
            obj.metadata.creation_timestamp = existing.metadata.creation_timestamp
            obj.metadata.deletion_timestamp = existing.metadata.deletion_timestamp
            obj.metadata.resource_version = self._next_rv()
            sh.objects[key] = obj
            self._maybe_raise_fence(kind, obj)
            self._notify(sh, MODIFIED, obj)
            finalized = self._maybe_finalize(sh, key)
            if not self._snapshot:
                out = serde.slow_deep_copy(obj)
            else:
                out = None
        self._finish_finalize(finalized, key[0])
        return out if out is not None else self._copy(obj)

    def patch_meta(self, kind: str, namespace: str, name: str,
                   fn: Callable[[ObjectMeta], None],
                   fence: Optional[int] = None) -> Any:
        """Server-side metadata patch (the adoption/release path: owner-ref
        merge patches, ref: pkg/controller/ref/service.go:126-164).  ``fn``
        mutates a write-time copy under the shard lock, so it cannot race
        other writers of this kind (and must not call back into other
        kinds); resourceVersion is bumped and watchers notified."""
        sh = self._shard(kind)
        finalized = None
        with sh:
            self._check_fence(kind, fence)
            existing = sh.objects.get((namespace, name))
            if existing is None:
                raise NotFound(f"{kind} {namespace}/{name} not found")
            obj = self._copy(existing)  # copy-on-write: snapshots are immutable
            fn(obj.metadata)
            obj.metadata.resource_version = self._next_rv()
            sh.objects[(namespace, name)] = obj
            self._notify(sh, MODIFIED, obj)
            finalized = self._maybe_finalize(sh, (namespace, name))
            if not self._snapshot:
                out = serde.slow_deep_copy(obj)
            else:
                out = None
        self._finish_finalize(finalized, namespace)
        return out if out is not None else self._copy(obj)

    def patch(self, kind: str, namespace: str, name: str, body: Dict,
              fence: Optional[int] = None) -> Any:
        """Full-object JSON merge patch (RFC 7386) — the PatchService analog
        (ref: pkg/controller/control/service.go:50-53), generalized to every
        kind.  Server-side under the shard lock, so it cannot race other
        writers; immutable metadata (uid, name/namespace, timestamps) is
        preserved, resourceVersion bumps, watchers see MODIFIED."""
        sh = self._shard(kind)
        finalized = None
        with sh:
            self._check_fence(kind, fence)
            existing = sh.objects.get((namespace, name))
            if existing is None:
                raise NotFound(f"{kind} {namespace}/{name} not found")
            # Status is a subresource: the real API server drops 'status'
            # from main-verb mutations when the subresource is enabled, so
            # a buggy client patch cannot clobber the updater's rollup
            # (which goes through update_status and its Conflict
            # semantics).  Done here so the in-process client and the REST
            # transport cannot diverge.
            if "status" in body:
                body = {k: v for k, v in body.items() if k != "status"}
            merged = serde.json_merge_patch(serde.to_dict(existing), body)
            obj = serde.from_dict(type(existing), merged)
            obj.metadata.namespace, obj.metadata.name = namespace, name
            obj.metadata.uid = existing.metadata.uid
            obj.metadata.creation_timestamp = existing.metadata.creation_timestamp
            obj.metadata.deletion_timestamp = existing.metadata.deletion_timestamp
            obj.metadata.resource_version = self._next_rv()
            sh.objects[(namespace, name)] = obj
            self._notify(sh, MODIFIED, obj)
            finalized = self._maybe_finalize(sh, (namespace, name))
            if not self._snapshot:
                out = serde.slow_deep_copy(obj)
            else:
                out = None
        self._finish_finalize(finalized, namespace)
        return out if out is not None else self._copy(obj)

    def update_status(self, kind: str, obj: Any,
                      fence: Optional[int] = None) -> Any:
        """Status-subresource style update: only .status is applied.  A
        stale resourceVersion raises Conflict (as the real subresource does);
        an empty resourceVersion means last-write-wins."""
        status = self._copy(obj.status)  # caller's object: copy pre-lock
        meta: ObjectMeta = obj.metadata
        key = (meta.namespace, meta.name)
        sh = self._shard(kind)
        with sh:
            self._check_fence(kind, fence)
            existing = sh.objects.get(key)
            if existing is None:
                raise NotFound(f"{kind} {key} not found")
            if meta.resource_version and meta.resource_version != existing.metadata.resource_version:
                raise Conflict(
                    f"{kind} {key}: status resourceVersion {meta.resource_version} "
                    f"!= {existing.metadata.resource_version}"
                )
            new = self._copy(existing)  # copy-on-write swap
            new.status = status
            new.metadata.resource_version = self._next_rv()
            sh.objects[key] = new
            self._notify(sh, MODIFIED, new)
            if not self._snapshot:
                return serde.slow_deep_copy(new)
        return self._copy(new)

    def update_progress(self, kind: str, namespace: str, name: str,
                        progress: Any, fence: Optional[int] = None) -> Any:
        """Progress-subresource update: only ``.status.progress`` is applied,
        last-write-wins (the workload is the sole writer for its own pod,
        like the kubelet for phase — no resourceVersion ping-pong on a
        periodic heartbeat).  The server stamps the beat time when the
        reporter left it 0, so liveness cannot be faked by a skewed clock."""
        progress = self._copy(progress)
        if not getattr(progress, "timestamp", 0.0):
            progress.timestamp = time.time()
        sh = self._shard(kind)
        with sh:
            self._check_fence(kind, fence)
            existing = sh.objects.get((namespace, name))
            if existing is None:
                raise NotFound(f"{kind} {namespace}/{name} not found")
            new = self._copy(existing)
            new.status.progress = progress
            new.metadata.resource_version = self._next_rv()
            sh.objects[(namespace, name)] = new
            self._notify(sh, MODIFIED, new)
            if not self._snapshot:
                return serde.slow_deep_copy(new)
        return self._copy(new)

    def delete(self, kind: str, namespace: str, name: str,
               cascade: bool = True, fence: Optional[int] = None) -> None:
        """Delete an object.  With finalizers present this is GRACEFUL, as
        on a real API server: deletionTimestamp is stamped and the object
        stays (MODIFIED) until every finalizer is removed via update/patch —
        at which point it is finalized (DELETED + cascade).  Without
        finalizers: immediate delete + (optionally) cascading GC of
        controller-owned objects — the capability the reference left as a
        stub."""
        sh = self._shard(kind)
        removed = None
        with sh:
            self._check_fence(kind, fence)
            obj = sh.objects.get((namespace, name))
            if obj is None:
                raise NotFound(f"{kind} {namespace}/{name} not found")
            if obj.metadata.finalizers:
                if obj.metadata.deletion_timestamp is None:
                    new = self._copy(obj)
                    new.metadata.deletion_timestamp = time.time()
                    new.metadata.resource_version = self._next_rv()
                    sh.objects[(namespace, name)] = new
                    self._notify(sh, MODIFIED, new)
                return
            sh.objects.pop((namespace, name))
            # The stored snapshot may still be referenced by readers:
            # stamp the delete on a copy, never in place.
            removed = self._copy(obj)
            removed.metadata.deletion_timestamp = time.time()
            # Deletes bump the RV too (as the real apiserver does): the
            # DELETED event needs its own slot in the watch cache, or a
            # client resuming from the create's RV would never replay it.
            removed.metadata.resource_version = self._next_rv()
            self._notify(sh, DELETED, removed)
        if cascade and removed is not None:
            self._cascade_delete(removed.metadata.uid, namespace)

    def _maybe_finalize(self, sh: _Shard, key: tuple) -> Optional[Any]:
        """Remove an object whose deletion was blocked on finalizers once
        the last finalizer is gone (k8s finalization semantics).  Runs
        under the caller's shard lock; returns the finalized snapshot so
        the caller cascades AFTER releasing the lock (cascading holds at
        most one shard lock at a time — the no-deadlock invariant)."""
        obj = sh.objects.get(key)
        if obj is None or obj.metadata.deletion_timestamp is None or obj.metadata.finalizers:
            return None
        sh.objects.pop(key)
        removed = self._copy(obj)
        removed.metadata.resource_version = self._next_rv()  # see delete()
        self._notify(sh, DELETED, removed)
        return removed

    def _finish_finalize(self, finalized: Optional[Any], namespace: str) -> None:
        if finalized is not None:
            self._cascade_delete(finalized.metadata.uid, namespace)

    def _cascade_delete(self, owner_uid: str, namespace: str) -> None:
        # Runs with NO shard lock held: each kind's victims are resolved
        # from the owner index under that kind's lock, then deleted through
        # the public path (which re-acquires per child) — shard locks never
        # nest, so cross-kind cascades cannot deadlock.  A child created
        # for a just-deleted owner after its shard was consulted is picked
        # up by the controller's next sync, as with the async GC on a real
        # cluster.  Index postings are re-verified against the live object
        # (adoption/release may have re-owned a child since the posting was
        # written); stale postings are pruned here.
        with self._shards_guard:
            kinds = list(self._shards)
        for kind in kinds:
            sh = self._shard(kind)
            with sh:
                posting = sh.owners.get(owner_uid)
                if not posting:
                    continue
                victims = []
                stale = []
                for key in posting:
                    ns, name = key
                    child = sh.objects.get(key)
                    ref = (get_controller_of(child.metadata)
                           if child is not None else None)
                    if child is None or ref is None or ref.uid != owner_uid:
                        stale.append(key)
                    elif ns == namespace:
                        victims.append(name)
                for key in stale:
                    posting.discard(key)
                if not posting:
                    sh.owners.pop(owner_uid, None)
            for name in victims:
                try:
                    self.delete(kind, namespace, name, cascade=True)
                except NotFound:
                    pass  # lost a race with a concurrent deleter: already gone

    def mark_deleting(self, kind: str, namespace: str, name: str,
                      fence: Optional[int] = None) -> Any:
        """Set deletionTimestamp without removing (graceful-deletion state,
        which FilterActivePods treats as inactive).  Deliberately does NOT
        finalize an object with no finalizers: the node agent owns the final
        delete, as a kubelet does for a terminating pod."""
        sh = self._shard(kind)
        with sh:
            self._check_fence(kind, fence)
            obj = sh.objects.get((namespace, name))
            if obj is None:
                raise NotFound(f"{kind} {namespace}/{name} not found")
            if obj.metadata.deletion_timestamp is None:
                new = self._copy(obj)
                new.metadata.deletion_timestamp = time.time()
                new.metadata.resource_version = self._next_rv()
                sh.objects[(namespace, name)] = new
                self._notify(sh, MODIFIED, new)
                obj = new
            if not self._snapshot:
                return serde.slow_deep_copy(obj)
        return self._copy(obj)

    def watch(self, kind: str, namespace: Optional[str] = None,
              since_rv: Optional[str] = None,
              bookmark: bool = False,
              max_queue: Optional[int] = None,
              auto_resume: bool = True) -> Watcher:
        """Open a watch stream.  ``since_rv`` resumes from a resourceVersion:
        every buffered event after it is replayed into the stream (exactly
        once, in write order, namespace-filtered) ahead of live events.
        Raises :class:`TooOldResourceVersion` when events after ``since_rv``
        have been evicted from the bounded watch cache — the client's only
        correct recovery then is a full re-list (410 Gone over REST).
        ``bookmark=True`` enqueues an initial BOOKMARK event carrying the
        current collection RV, so even a stream that never receives an
        event holds a fresh resume point.  Registration and replay happen
        in one critical section: no live write can interleave into (or
        duplicate) the replayed prefix.

        ``max_queue`` bounds the stream's queue (None = the store default;
        0 = unbounded); ``auto_resume`` picks the overflow recovery — see
        :class:`Watcher`."""
        sh = self._shard(kind)
        with sh:
            if since_rv is not None:
                since = int(since_rv)
                if since < sh.evicted_rv:
                    raise TooOldResourceVersion(
                        f"{kind}: resourceVersion {since} is too old "
                        f"(watch cache begins after {sh.evicted_rv})")
            w = Watcher(self, kind, namespace,
                        max_queue=(self._watch_queue_size if max_queue is None
                                   else max_queue),
                        auto_resume=auto_resume)
            if since_rv is not None:
                w._last_rv = since
                replayed = 0
                for rv, ev in sh.watch_cache:
                    if rv <= since:
                        continue
                    if namespace is not None and ev.object.metadata.namespace != namespace:
                        continue
                    w.queue.put(ev)
                    replayed += 1
                if replayed:
                    self._c_replayed.inc(replayed)
            sh.watchers.append(w)
            if bookmark:
                w.queue.put(_bookmark_event(str(self._rv)))
            return w

    def request_bookmark(self, w: Watcher) -> None:
        """Enqueue a BOOKMARK carrying the current collection RV into
        ``w``'s stream (the apiserver's periodic watch bookmarks: they keep
        an idle or namespace-filtered stream's resume point fresh).  Under
        the shard lock, every same-kind write with rv ≤ the stamped RV has
        already enqueued its event ahead of the bookmark — so resuming from
        a bookmark RV can never skip an earlier event."""
        with self._shard(w.kind):
            if not w._dropped and not w._stopped:
                w.queue.put(_bookmark_event(str(self._rv)))

    # -- analysis hooks (opt-in; zero-cost when detached) ---------------------

    #: Public ops wrapped by :meth:`attach_recorder` — exactly the surface
    #: the linearizability checker's sequential spec models
    #: (analysis/linearize.py).  ``list`` rides through ``list_with_rv``;
    #: ``watch`` streams are the watch-delivery checker's territory.
    RECORDED_OPS = ("create", "get", "update", "update_status", "patch",
                    "patch_meta", "update_progress", "mark_deleting",
                    "delete", "list_with_rv")

    def attach_recorder(self, recorder) -> None:
        """Start recording op histories into ``recorder`` (an
        ``analysis.linearize.HistoryRecorder``-shaped object: ``clock()``
        and ``record(op, args, kwargs, result, error, t0, t1)``).

        Implementation is instance-level method wrapping: each op in
        :data:`RECORDED_OPS` gets a shadowing instance attribute that
        timestamps the call, delegates to the class method, and reports
        result or APIError.  With no recorder attached the instance dict
        is untouched and calls dispatch straight to the unmodified class
        methods — the disabled path costs literally nothing, which is
        what lets the hook ship enabled-able in production builds
        (gated by ``bench.py --scale N --record-history`` staying within
        noise of the baseline)."""
        if getattr(self, "_recorder", None) is not None:
            raise RuntimeError("a recorder is already attached")
        for op in self.RECORDED_OPS:
            inner = getattr(type(self), op)

            def wrapper(*a, _op=op, _inner=inner, **kw):
                t0 = recorder.clock()
                try:
                    out = _inner(self, *a, **kw)
                except APIError as e:
                    recorder.record(_op, a, kw, None, e,
                                    t0, recorder.clock())
                    raise
                recorder.record(_op, a, kw, out, None, t0, recorder.clock())
                return out

            self.__dict__[op] = wrapper
        self._recorder = recorder

    def detach_recorder(self) -> None:
        """Remove the recording wrappers; the store returns to the
        zero-overhead class-method dispatch."""
        for op in self.RECORDED_OPS:
            self.__dict__.pop(op, None)
        self._recorder = None

    def drop_watchers(self, kind: str, exclude: tuple = ()) -> int:
        """Force-drop every live watcher of ``kind`` (minus ``exclude``) —
        the chaos hook the simulation driver (analysis/simcheck.py) uses
        to drop streams mid-batch.  Exactly the eviction the write path
        applies to an overflowing consumer, under the same shard lock:
        buffered events drain, the sentinel lands after them, auto-resume
        watchers replay the window from the watch cache on their next
        ``next()``.  Returns the number of watchers dropped."""
        sh = self._shard(kind)
        with sh:
            dropped = 0
            keep: List[Watcher] = []
            for w in sh.watchers:
                if w in exclude:
                    keep.append(w)
                    continue
                w._dropped = True
                w.queue.put(None)
                dropped += 1
            sh.watchers = keep
        return dropped

    # -- HA: durability (WAL-over-snapshot recovery; ha/wal.py) ---------------

    def export_state(self) -> Dict[str, Any]:
        """Full-store state capture for snapshots and RV-identity checks:
        ``{rv, uid, kinds: {kind: [{cls, obj}, ...]}}``.

        The counters are read FIRST: a concurrent writer may land between
        the counter read and its kind's capture, in which case its record
        appears both in the captured state and in the WAL tail kept by
        ``compact`` (rv > this state's rv) — replay is an idempotent
        upsert, so the overlap is harmless.  Shard locks are taken one at
        a time, never nested."""
        from ..ha.wal import type_tag

        with self._meta_lock:
            rv0, uid0 = self._rv, self._uid
        kinds: Dict[str, list] = {}
        with self._shards_guard:
            names = list(self._shards)
        for kind in names:
            sh = self._shard(kind)
            with sh:
                kinds[kind] = [
                    {"cls": type_tag(o), "obj": serde.to_dict(o)}
                    for o in sh.objects.values()
                ]
        return {"rv": rv0, "uid": uid0, "kinds": kinds}

    def compact_wal(self) -> int:
        """Snapshot the store and truncate the WAL to records newer than
        the snapshot (ha/wal.py compact).  Returns records kept."""
        if self._wal is None:
            raise RuntimeError("store has no WAL attached")
        return self._wal.compact(self.export_state())

    def flush_wal(self) -> None:
        """fsync any buffered WAL tail (no-op without a WAL) — the
        FakeAPIServer shutdown hook, so a stopped server's journal is
        byte-complete on disk."""
        if self._wal is not None:
            self._wal.flush()

    @classmethod
    def recover(cls, wal, watch_cache_size: int = 1024,
                sharded: bool = True,
                watch_queue_size: int = 8192) -> "ObjectStore":
        """Rebuild a store from WAL-over-snapshot: load the newest intact
        snapshot, replay every journaled record after it, and resume
        appending to the same WAL.  The result is RV-identical to the
        crashed store — same objects, same resourceVersions, same uid
        counter, and the same per-kind watch-cache tail, so a watch
        client resuming with its pre-crash RV replays exactly the events
        it missed (verified by tests/test_ha.py + the PR-11 checkers
        under ``kctpu check --crash-restart``)."""
        import time as _time

        from ..ha.wal import materialize, replay_seconds_gauge

        t0 = _time.perf_counter()
        store = cls(watch_cache_size=watch_cache_size, sharded=sharded,
                    watch_queue_size=watch_queue_size)
        max_rv = 0
        max_uid = 0
        snap = wal.load_snapshot()
        if snap is not None:
            snap_rv = int(snap["rv"])
            for kind, entries in snap["kinds"].items():
                sh = store._shard(kind)
                with sh:
                    for e in entries:
                        obj = materialize(e["cls"], e["obj"])
                        m = obj.metadata
                        sh.objects[(m.namespace, m.name)] = obj
                        cls._index_owner(sh, obj)
                        max_uid = max(max_uid, _uid_seq(m.uid))
                        rv = int(m.resource_version or 0)
                        if rv > max_rv:
                            max_rv = rv
                    # Events at or before the snapshot are not in the
                    # rebuilt ring: resumes below it are exactly 410s.
                    sh.evicted_rv = max(sh.evicted_rv, snap_rv)
            max_rv = max(max_rv, snap_rv)
            max_uid = max(max_uid, int(snap.get("uid", 0)))
        for rec in wal.replay():
            rv, uid = store._replay_apply(rec)
            max_rv = max(max_rv, rv)
            max_uid = max(max_uid, uid)
        with store._meta_lock:
            store._rv = max(store._rv, max_rv)
            store._uid = max(store._uid, max_uid)
        store._wal = wal
        replay_seconds_gauge().set(_time.perf_counter() - t0)
        return store

    def _replay_apply(self, rec) -> Tuple[int, int]:
        """Apply one WAL record during recovery: upsert/remove the stored
        object and rebuild the watch-cache ring through the same bounded
        eviction the live path uses.  No watchers exist yet (the store is
        private to recover()), so nothing is notified; nothing re-appends
        to the WAL.  Idempotent: replaying a record the snapshot already
        contains just rewrites the same snapshot object."""
        obj = rec.materialize()
        sh = self._shard(rec.kind)
        with sh:
            key = (obj.metadata.namespace, obj.metadata.name)
            if rec.ev == DELETED:
                sh.objects.pop(key, None)
                self._index_owner(sh, obj, removed=True)
            else:
                sh.objects[key] = obj
                self._index_owner(sh, obj)
            buf = sh.watch_cache
            buf.append((rec.rv, WatchEvent(rec.ev, obj)))
            if len(buf) > self._watch_cache_size:
                evicted_rv, _ = buf.popleft()
                if evicted_rv > sh.evicted_rv:
                    sh.evicted_rv = evicted_rv
        return rec.rv, _uid_seq(obj.metadata.uid)

    # -- observability --------------------------------------------------------

    def lock_wait_stats(self) -> Dict[str, Dict[str, float]]:
        """Per-kind shard-lock wait statistics since construction:
        ``{kind: {acquires, contended, overflows, wait_sum_s, wait_max_s,
        p50_s, p99_s}}``.  Percentiles are conservative bucket upper
        bounds (``bucket_quantile``)."""
        out: Dict[str, Dict[str, float]] = {}
        for kind, sh in list(self._shards.items()):
            counts = list(sh.wait_counts)
            total = sum(counts)
            out[kind] = {
                "acquires": total,
                "contended": sh.contended,
                "overflows": sh.overflows,
                "wait_sum_s": sh.wait_sum,
                "wait_max_s": sh.wait_max,
                "p50_s": bucket_quantile(LOCK_WAIT_BUCKETS, counts, 0.50),
                "p99_s": bucket_quantile(LOCK_WAIT_BUCKETS, counts, 0.99),
            }
        return out

    def _collect_families(self) -> List[Family]:
        """Scrape-time store families: per-shard lock-wait histogram,
        object depth, and watch-queue depth/overflow — rendered from the
        shard-local counters so the hot path never touches an instrument
        lock shared across shards."""
        wait_fam = Family(
            "kctpu_store_lock_wait_seconds", "histogram",
            "Time spent waiting to acquire a store shard lock, per kind")
        depth_fam = Family(
            "kctpu_store_shard_depth", "gauge",
            "Objects held per store shard (kind)")
        qdepth_fam = Family(
            "kctpu_watch_queue_depth", "gauge",
            "Deepest live watcher queue per kind")
        overflow_fam = Family(
            "kctpu_watch_queue_overflows_total", "counter",
            "Watch streams dropped because a slow consumer overflowed its "
            "bounded queue (recovered via RV-resume replay)")
        contended_fam = Family(
            "kctpu_store_lock_contended_total", "counter",
            "Store shard-lock acquisitions that had to wait")
        for kind, sh in sorted(self._shards.items()):
            base = {"kind": kind}
            counts = list(sh.wait_counts)
            acc = 0
            for ub, c in zip(LOCK_WAIT_BUCKETS, counts):
                acc += c
                wait_fam.samples.append(
                    Sample("_bucket", {**base, "le": repr(float(ub))}, acc))
            total = sum(counts)
            wait_fam.samples.append(Sample("_bucket", {**base, "le": "+Inf"}, total))
            wait_fam.samples.append(Sample("_sum", base, sh.wait_sum))
            wait_fam.samples.append(Sample("_count", base, total))
            depth_fam.samples.append(Sample("", base, len(sh.objects)))
            with sh.lock:
                depth = max((w.queue.qsize() for w in sh.watchers), default=0)
            qdepth_fam.samples.append(Sample("", base, depth))
            overflow_fam.samples.append(Sample("", base, sh.overflows))
            contended_fam.samples.append(Sample("", base, sh.contended))
        return [wait_fam, depth_fam, qdepth_fam, overflow_fam, contended_fam]
