"""In-memory object store with k8s API-server semantics.

Reproduces the behaviors the reference's controller correctness depends on
(SURVEY.md §7 "hard parts"):

- monotonically increasing resourceVersions, bumped on every write;
- optimistic concurrency: update with a stale resourceVersion -> Conflict
  (the reference does full-object Update with no retry at
  pkg/controller/controller.go:643-649; our controller layers retry on top);
- ``generateName`` materialization (base + 5 random alphanumerics, ref:
  vendor/k8s.io/kubernetes/pkg/api/v1/generate.go:48-72);
- watch streams that deliver ADDED/MODIFIED/DELETED in write order, each
  carrying one deep copy shared read-only by all watchers (watchers can
  never mutate the store; see ``_notify``);
- a bounded per-kind **watch cache** of recent ``(rv, event)`` pairs (the
  kube-apiserver watch cache): ``watch(kind, since_rv=...)`` replays the
  buffered events after ``since_rv`` before going live, so a client that
  lost its stream resumes from its last-seen resourceVersion instead of
  re-listing the collection; a resume point older than the buffer raises
  :class:`TooOldResourceVersion` (HTTP 410 Gone over REST), and
  ``list_with_rv`` hands out the collection RV so every LIST is a resume
  point;
- deletionTimestamp + cascading garbage collection of controller-owned
  objects (net-new: the reference's delete handlers are stubs,
  pkg/controller/controller.go:522-524, 601-603).

Everything is guarded by one RLock; watch queues are unbounded
``queue.Queue`` so writers never block on slow watchers.
"""

from __future__ import annotations

import collections
import queue
import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..api.meta import ObjectMeta, get_controller_of, matches_selector
from ..obs.metrics import REGISTRY
from ..utils import serde
from ..utils.names import generate_name


class APIError(Exception):
    pass


class NotFound(APIError):
    pass


class AlreadyExists(APIError):
    pass


class Conflict(APIError):
    """Stale resourceVersion on update (optimistic-concurrency failure)."""


class Invalid(APIError):
    pass


class TooOldResourceVersion(APIError):
    """The requested resume resourceVersion has fallen out of the bounded
    watch cache (HTTP 410 Gone over REST): the client must re-list."""


# Watch event types (ref: watch.Added/Modified/Deleted in apimachinery;
# BOOKMARK per watch.Bookmark — an RV checkpoint carrying no object change).
ADDED = "ADDED"
MODIFIED = "MODIFIED"
DELETED = "DELETED"
BOOKMARK = "BOOKMARK"


@dataclass
class WatchEvent:
    type: str
    object: Any  # deep copy of the stored object


@dataclass
class Bookmark:
    """BOOKMARK event payload: only ``metadata.resource_version`` is
    meaningful — the RV through which the carrying stream is complete."""

    metadata: ObjectMeta


def _bookmark_event(rv: str) -> WatchEvent:
    return WatchEvent(BOOKMARK, Bookmark(metadata=ObjectMeta(resource_version=rv)))


class Watcher:
    """One watch stream: an unbounded queue of :class:`WatchEvent`."""

    def __init__(self, store: "ObjectStore", kind: str, namespace: Optional[str]):
        self._store = store
        self.kind = kind
        self.namespace = namespace
        self.queue: "queue.Queue[Optional[WatchEvent]]" = queue.Queue()
        self._stopped = False

    def next(self, timeout: Optional[float] = None) -> Optional[WatchEvent]:
        """Blocking pop; None on stop or timeout."""
        try:
            return self.queue.get(timeout=timeout)
        except queue.Empty:
            return None

    def stop(self) -> None:
        if not self._stopped:
            self._stopped = True
            self._store._remove_watcher(self)
            self.queue.put(None)  # sentinel to unblock consumers


class ObjectStore:
    """The in-memory API server. Collections are keyed by plural kind
    ("tfjobs", "pods", "services"); objects by (namespace, name)."""

    def __init__(self, watch_cache_size: int = 1024):
        self._lock = threading.RLock()
        self._objects: Dict[str, Dict[tuple, Any]] = {}
        self._watchers: Dict[str, List[Watcher]] = {}
        self._rv = 0
        self._uid = 0
        # Per-kind ring buffer of recent (rv, event) pairs — the
        # kube-apiserver watch cache.  A watch(since_rv=...) replays from
        # here; _evicted_rv records the newest rv ever evicted per kind, so
        # a resume point older than the buffer is detected exactly (410).
        self._watch_cache_size = watch_cache_size
        self._watch_cache: Dict[str, "collections.deque[Tuple[int, WatchEvent]]"] = {}
        self._evicted_rv: Dict[str, int] = {}
        self._c_replayed = REGISTRY.counter(
            "kctpu_watch_replayed_events_total",
            "Watch events replayed from the server watch cache on "
            "RV-resumed watch connects")
        self._g_cache_depth = REGISTRY.gauge(
            "kctpu_watch_cache_depth",
            "Buffered (rv, event) pairs in the per-kind server watch cache",
            ("kind",))

    # -- internals -----------------------------------------------------------

    def _next_rv(self) -> str:
        self._rv += 1
        return str(self._rv)

    def _next_uid(self) -> str:
        self._uid += 1
        return f"uid-{self._uid}"

    def _collection(self, kind: str) -> Dict[tuple, Any]:
        return self._objects.setdefault(kind, {})

    def _notify(self, kind: str, ev_type: str, obj: Any) -> None:
        # Single-serialization fan-out: ONE deep copy per event, shared by
        # every watcher's queue AND the per-kind watch cache (the apiserver
        # analog: one encode, N streams).  Per-watcher copies made this
        # O(watchers × object size) under the global lock — with 4+
        # watchers per kind (controller informer, kubelet, REST streams)
        # the dominant write-path cost.  The shared copy still can't mutate
        # the store; watch consumers treat event objects as read-only
        # (informers hand out copies on the mutating read paths).  The copy
        # is made even with zero live watchers: a disconnected client's
        # resume depends on exactly the events it wasn't there to see.
        shared = serde.deep_copy(obj)
        ev = WatchEvent(ev_type, shared)
        buf = self._watch_cache.get(kind)
        if buf is None:
            buf = self._watch_cache[kind] = collections.deque()
        buf.append((int(shared.metadata.resource_version), ev))
        if len(buf) > self._watch_cache_size:
            evicted_rv, _ = buf.popleft()
            if evicted_rv > self._evicted_rv.get(kind, 0):
                self._evicted_rv[kind] = evicted_rv
        self._g_cache_depth.labels(kind).set(len(buf))
        for w in self._watchers.get(kind, []):
            if w.namespace is None or w.namespace == obj.metadata.namespace:
                w.queue.put(ev)

    def _remove_watcher(self, w: Watcher) -> None:
        with self._lock:
            lst = self._watchers.get(w.kind, [])
            if w in lst:
                lst.remove(w)

    # -- API surface ---------------------------------------------------------

    def create(self, kind: str, obj: Any) -> Any:
        with self._lock:
            meta: ObjectMeta = obj.metadata
            obj = serde.deep_copy(obj)
            meta = obj.metadata
            if not meta.name:
                if not meta.generate_name:
                    raise Invalid("either name or generateName is required")
                # Retry on (unlikely) suffix collision, as the apiserver does.
                for _ in range(8):
                    candidate = generate_name(meta.generate_name)
                    if (meta.namespace, candidate) not in self._collection(kind):
                        meta.name = candidate
                        break
                else:
                    raise AlreadyExists(f"could not generate unique name for {meta.generate_name}")
            key = (meta.namespace, meta.name)
            if key in self._collection(kind):
                raise AlreadyExists(f"{kind} {key} already exists")
            meta.uid = self._next_uid()
            meta.resource_version = self._next_rv()
            meta.creation_timestamp = time.time()
            self._collection(kind)[key] = obj
            self._notify(kind, ADDED, obj)
            return serde.deep_copy(obj)

    def get(self, kind: str, namespace: str, name: str) -> Any:
        """A quorum/live read — this is what the adoption path's
        ``canAdoptFunc`` uses to re-check UIDs (ref: pkg/controller/
        helper.go:137-146, RecheckDeletionTimestamp at
        controller_ref_manager.go:373-385)."""
        with self._lock:
            obj = self._collection(kind).get((namespace, name))
            if obj is None:
                raise NotFound(f"{kind} {namespace}/{name} not found")
            return serde.deep_copy(obj)

    def list(
        self,
        kind: str,
        namespace: Optional[str] = None,
        selector: Optional[Dict[str, str]] = None,
    ) -> List[Any]:
        with self._lock:
            out = []
            for (ns, _), obj in self._collection(kind).items():
                if namespace is not None and ns != namespace:
                    continue
                if selector is not None and not matches_selector(obj.metadata.labels, selector):
                    continue
                out.append(serde.deep_copy(obj))
            return out

    def list_with_rv(
        self,
        kind: str,
        namespace: Optional[str] = None,
        selector: Optional[Dict[str, str]] = None,
    ) -> Tuple[List[Any], str]:
        """list() plus the collection resourceVersion (ListMeta.resourceVersion
        on a real API server): the resume point a client hands back as
        ``watch(since_rv=...)`` so a stream can start exactly where the
        LIST's snapshot ends — no gap, no re-list."""
        with self._lock:
            return self.list(kind, namespace, selector), str(self._rv)

    def update(self, kind: str, obj: Any) -> Any:
        with self._lock:
            meta: ObjectMeta = obj.metadata
            key = (meta.namespace, meta.name)
            existing = self._collection(kind).get(key)
            if existing is None:
                raise NotFound(f"{kind} {key} not found")
            if meta.resource_version and meta.resource_version != existing.metadata.resource_version:
                raise Conflict(
                    f"{kind} {key}: resourceVersion {meta.resource_version} "
                    f"!= {existing.metadata.resource_version}"
                )
            obj = serde.deep_copy(obj)
            # uid, creation and deletion timestamps are immutable via update.
            obj.metadata.uid = existing.metadata.uid
            obj.metadata.creation_timestamp = existing.metadata.creation_timestamp
            obj.metadata.deletion_timestamp = existing.metadata.deletion_timestamp
            obj.metadata.resource_version = self._next_rv()
            self._collection(kind)[key] = obj
            self._notify(kind, MODIFIED, obj)
            out = serde.deep_copy(obj)
            self._maybe_finalize(kind, key)
            return out

    def patch_meta(self, kind: str, namespace: str, name: str,
                   fn: Callable[[ObjectMeta], None]) -> Any:
        """Server-side metadata patch (the adoption/release path: owner-ref
        merge patches, ref: pkg/controller/ref/service.go:126-164).  ``fn``
        mutates the live metadata under the lock, so it cannot race other
        writers; resourceVersion is bumped and watchers notified."""
        with self._lock:
            obj = self._collection(kind).get((namespace, name))
            if obj is None:
                raise NotFound(f"{kind} {namespace}/{name} not found")
            fn(obj.metadata)
            obj.metadata.resource_version = self._next_rv()
            self._notify(kind, MODIFIED, obj)
            out = serde.deep_copy(obj)
            self._maybe_finalize(kind, (namespace, name))
            return out

    def patch(self, kind: str, namespace: str, name: str, body: Dict) -> Any:
        """Full-object JSON merge patch (RFC 7386) — the PatchService analog
        (ref: pkg/controller/control/service.go:50-53), generalized to every
        kind.  Server-side under the lock, so it cannot race other writers;
        immutable metadata (uid, name/namespace, timestamps) is preserved,
        resourceVersion bumps, watchers see MODIFIED."""
        with self._lock:
            existing = self._collection(kind).get((namespace, name))
            if existing is None:
                raise NotFound(f"{kind} {namespace}/{name} not found")
            # Status is a subresource: the real API server drops 'status'
            # from main-verb mutations when the subresource is enabled, so
            # a buggy client patch cannot clobber the updater's rollup
            # (which goes through update_status and its Conflict
            # semantics).  Done here so the in-process client and the REST
            # transport cannot diverge.
            if "status" in body:
                body = {k: v for k, v in body.items() if k != "status"}
            merged = serde.json_merge_patch(serde.to_dict(existing), body)
            obj = serde.from_dict(type(existing), merged)
            obj.metadata.namespace, obj.metadata.name = namespace, name
            obj.metadata.uid = existing.metadata.uid
            obj.metadata.creation_timestamp = existing.metadata.creation_timestamp
            obj.metadata.deletion_timestamp = existing.metadata.deletion_timestamp
            obj.metadata.resource_version = self._next_rv()
            self._collection(kind)[(namespace, name)] = obj
            self._notify(kind, MODIFIED, obj)
            out = serde.deep_copy(obj)
            self._maybe_finalize(kind, (namespace, name))
            return out

    def update_status(self, kind: str, obj: Any) -> Any:
        """Status-subresource style update: only .status is applied.  A
        stale resourceVersion raises Conflict (as the real subresource does);
        an empty resourceVersion means last-write-wins."""
        with self._lock:
            meta: ObjectMeta = obj.metadata
            key = (meta.namespace, meta.name)
            existing = self._collection(kind).get(key)
            if existing is None:
                raise NotFound(f"{kind} {key} not found")
            if meta.resource_version and meta.resource_version != existing.metadata.resource_version:
                raise Conflict(
                    f"{kind} {key}: status resourceVersion {meta.resource_version} "
                    f"!= {existing.metadata.resource_version}"
                )
            existing.status = serde.deep_copy(obj.status)
            existing.metadata.resource_version = self._next_rv()
            self._notify(kind, MODIFIED, existing)
            return serde.deep_copy(existing)

    def update_progress(self, kind: str, namespace: str, name: str,
                        progress: Any) -> Any:
        """Progress-subresource update: only ``.status.progress`` is applied,
        last-write-wins (the workload is the sole writer for its own pod,
        like the kubelet for phase — no resourceVersion ping-pong on a
        periodic heartbeat).  The server stamps the beat time when the
        reporter left it 0, so liveness cannot be faked by a skewed clock."""
        with self._lock:
            existing = self._collection(kind).get((namespace, name))
            if existing is None:
                raise NotFound(f"{kind} {namespace}/{name} not found")
            progress = serde.deep_copy(progress)
            if not getattr(progress, "timestamp", 0.0):
                progress.timestamp = time.time()
            existing.status.progress = progress
            existing.metadata.resource_version = self._next_rv()
            self._notify(kind, MODIFIED, existing)
            return serde.deep_copy(existing)

    def delete(self, kind: str, namespace: str, name: str, cascade: bool = True) -> None:
        """Delete an object.  With finalizers present this is GRACEFUL, as
        on a real API server: deletionTimestamp is stamped and the object
        stays (MODIFIED) until every finalizer is removed via update/patch —
        at which point it is finalized (DELETED + cascade).  Without
        finalizers: immediate delete + (optionally) cascading GC of
        controller-owned objects — the capability the reference left as a
        stub."""
        with self._lock:
            obj = self._collection(kind).get((namespace, name))
            if obj is None:
                raise NotFound(f"{kind} {namespace}/{name} not found")
            if obj.metadata.finalizers:
                if obj.metadata.deletion_timestamp is None:
                    obj.metadata.deletion_timestamp = time.time()
                    obj.metadata.resource_version = self._next_rv()
                    self._notify(kind, MODIFIED, obj)
                return
            self._collection(kind).pop((namespace, name))
            obj.metadata.deletion_timestamp = time.time()
            # Deletes bump the RV too (as the real apiserver does): the
            # DELETED event needs its own slot in the watch cache, or a
            # client resuming from the create's RV would never replay it.
            obj.metadata.resource_version = self._next_rv()
            self._notify(kind, DELETED, obj)
            if cascade:
                self._cascade_delete(obj.metadata.uid, namespace)

    def _maybe_finalize(self, kind: str, key: tuple) -> bool:
        """Remove an object whose deletion was blocked on finalizers once
        the last finalizer is gone (k8s finalization semantics)."""
        obj = self._collection(kind).get(key)
        if obj is None or obj.metadata.deletion_timestamp is None or obj.metadata.finalizers:
            return False
        self._collection(kind).pop(key)
        obj.metadata.resource_version = self._next_rv()  # see delete()
        self._notify(kind, DELETED, obj)
        self._cascade_delete(obj.metadata.uid, key[0])
        return True

    def _cascade_delete(self, owner_uid: str, namespace: str) -> None:
        for kind in list(self._objects):
            for (ns, name), child in list(self._collection(kind).items()):
                if ns != namespace:
                    continue
                ref = get_controller_of(child.metadata)
                if ref is not None and ref.uid == owner_uid:
                    self.delete(kind, ns, name, cascade=True)

    def mark_deleting(self, kind: str, namespace: str, name: str) -> Any:
        """Set deletionTimestamp without removing (graceful-deletion state,
        which FilterActivePods treats as inactive).  Deliberately does NOT
        finalize an object with no finalizers: the node agent owns the final
        delete, as a kubelet does for a terminating pod."""
        with self._lock:
            obj = self._collection(kind).get((namespace, name))
            if obj is None:
                raise NotFound(f"{kind} {namespace}/{name} not found")
            if obj.metadata.deletion_timestamp is None:
                obj.metadata.deletion_timestamp = time.time()
                obj.metadata.resource_version = self._next_rv()
                self._notify(kind, MODIFIED, obj)
            return serde.deep_copy(obj)

    def watch(self, kind: str, namespace: Optional[str] = None,
              since_rv: Optional[str] = None,
              bookmark: bool = False) -> Watcher:
        """Open a watch stream.  ``since_rv`` resumes from a resourceVersion:
        every buffered event after it is replayed into the stream (exactly
        once, in write order, namespace-filtered) ahead of live events.
        Raises :class:`TooOldResourceVersion` when events after ``since_rv``
        have been evicted from the bounded watch cache — the client's only
        correct recovery then is a full re-list (410 Gone over REST).
        ``bookmark=True`` enqueues an initial BOOKMARK event carrying the
        current collection RV, so even a stream that never receives an
        event holds a fresh resume point.  Registration and replay happen
        in one critical section: no live write can interleave into (or
        duplicate) the replayed prefix."""
        with self._lock:
            if since_rv is not None:
                since = int(since_rv)
                if since < self._evicted_rv.get(kind, 0):
                    raise TooOldResourceVersion(
                        f"{kind}: resourceVersion {since} is too old "
                        f"(watch cache begins after "
                        f"{self._evicted_rv.get(kind, 0)})")
            w = Watcher(self, kind, namespace)
            if since_rv is not None:
                replayed = 0
                for rv, ev in self._watch_cache.get(kind, ()):
                    if rv <= since:
                        continue
                    if namespace is not None and ev.object.metadata.namespace != namespace:
                        continue
                    w.queue.put(ev)
                    replayed += 1
                if replayed:
                    self._c_replayed.inc(replayed)
            self._watchers.setdefault(kind, []).append(w)
            if bookmark:
                w.queue.put(_bookmark_event(str(self._rv)))
            return w

    def request_bookmark(self, w: Watcher) -> None:
        """Enqueue a BOOKMARK carrying the current collection RV into
        ``w``'s stream (the apiserver's periodic watch bookmarks: they keep
        an idle or namespace-filtered stream's resume point fresh).  Under
        the store lock, every write with rv ≤ the stamped RV has already
        enqueued its event ahead of the bookmark — so resuming from a
        bookmark RV can never skip an earlier event."""
        with self._lock:
            w.queue.put(_bookmark_event(str(self._rv)))
