"""Event-driven simulated kubelet: 50k pods on O(1) threads.

``FakeKubelet`` drives every pod with a dedicated thread
(``_drive_and_reap``): perfect for executed pods (the thread babysits a real
subprocess) and fine at ``--scale 200``, but a 10k-job / 50k-pod cluster
simulation would need ~50k OS threads — hundreds of MB of stacks and a GIL
convoy long before the control plane itself is the bottleneck.

``SimKubelet`` replaces the thread-per-pod model with a **timer wheel**: one
loop thread owns a heap of ``(due, seq, pod-key, action)`` events and drives
every simulated pod's Pending → Running → Succeeded/Failed transitions (plus
coarse progress beats) through it.  Thread count is constant in pod count;
per-transition cost is O(log pods).

Semantics are the *same* ``PhasePolicy`` contract the threaded kubelet
implements — pending/run clocks, per-job run overrides, run-forever replica
types, ``fail_once`` injection, heartbeat beats with ``suspend_heartbeats``
stall injection, TPU gang admission gating with queue-reason publishing,
warm/cold gang start costs, injected failures (``chaos_kill`` /
``fail_slice`` / scheduler evictions), and node-side idle-gang reaping —
asserted equivalent per scenario by tests/test_simkubelet.py.  Executed
(subprocess/warm-pool) pods are deliberately out of scope: a pod whose
container command actually runs needs its babysitter thread, and those paths
stay on ``FakeKubelet`` untouched.

Selection: ``bench.py --scale N --simulated`` (the scale envelope bench) or
constructing :class:`SimKubelet` wherever a ``FakeKubelet(execute=False)``
went.
"""

from __future__ import annotations

import heapq
import threading
import time
from typing import Dict, List, Optional, Set

from ..api.core import (
    PHASE_FAILED,
    PHASE_PENDING,
    PHASE_RUNNING,
    PHASE_SUCCEEDED,
    Pod,
)
from ..api.labels import ANNOTATION_GANG_NAME
from ..obs.metrics import REGISTRY
from ..utils import locks
from .client import Cluster
from .kubelet import PhasePolicy
from .store import ADDED, APIError, DELETED, MODIFIED, NotFound
from .tpu import pod_requests_tpu

# Timer actions (one per phase-machine edge).
_START = "start"        # gate / pending clock -> Running
_OFFER = "offer"        # retry TPU gang admission
_WARMUP = "warmup"      # cold/warm start delay elapsed -> pending clock
_FINISH = "finish"      # run clock elapsed -> terminal phase
_BEAT = "beat"          # heartbeat tick while Running

# Gang admission poll cadence — matches FakeKubelet._gate_tpu_pod's 5 ms
# sleep, so queue-wait distributions are comparable across modes.
_OFFER_TICK_S = 0.005
# Every Nth failed offer republishes the queue reason (FakeKubelet ticks
# ticks % 10 == 1 on the same cadence).
_REASON_EVERY = 10
# Node-side idle-gang reap cadence (FakeKubelet: 0.5 s).
_REAP_EVERY_S = 0.5


class _SimPod:
    """Per-pod state the timer events act on."""

    __slots__ = ("pod", "gone", "step", "offer_ticks", "last_reason",
                 "finish_at", "outcome")

    def __init__(self, pod: Pod):
        self.pod = pod
        self.gone = False          # DELETED / deletionTimestamp observed
        self.step = 0              # heartbeat step counter
        self.offer_ticks = 0
        self.last_reason = ""
        self.finish_at = 0.0       # monotonic deadline of the run clock
        self.outcome = PHASE_SUCCEEDED  # decided (once) at start time


class SimKubelet:
    """Drives simulated pod phases from one timer-wheel loop.

    Public surface mirrors the ``FakeKubelet`` operations that make sense
    without subprocesses: ``start``/``stop``, ``set_phase``,
    ``suspend_heartbeats``/``resume_heartbeats``, ``chaos_kill``,
    ``fail_slice``, and ``logs`` (always empty — simulated pods produce no
    output, exactly like FakeKubelet's simulated mode)."""

    def __init__(
        self,
        cluster: Cluster,
        policy: Optional[PhasePolicy] = None,
        inventory=None,
    ):
        self.cluster = cluster
        self.policy = policy or PhasePolicy()
        self.inventory = inventory
        self._pods: Dict[str, _SimPod] = {}
        # (due_monotonic, seq, key, action) — the timer wheel.
        self._timers: List[tuple] = []
        self._seq = 0
        self._hb_suspended = False
        self._injected_failures: Set[str] = set()
        self._injected_lock = locks.named_lock("simkubelet.injected")
        self._warm_gangs: Set[str] = set()
        self._stop = threading.Event()
        self._watcher = None
        self._main: Optional[threading.Thread] = None
        self._c_starts = REGISTRY.counter(
            "kctpu_pod_starts_total",
            "Pod process starts by mode (warm = forked from the zygote / "
            "warm gang readmission; cold = fresh interpreter)", ("mode",))
        g_pods = REGISTRY.gauge(
            "kctpu_sim_pods",
            "Pods currently driven by the event-driven simulated kubelet")
        g_pods.set_function(lambda: len(self._pods))
        g_timers = REGISTRY.gauge(
            "kctpu_sim_timer_depth",
            "Pending timer-wheel events in the simulated kubelet")
        g_timers.set_function(lambda: len(self._timers))
        if inventory is not None and hasattr(inventory, "set_evictor"):
            inventory.set_evictor(self._evict_pods)

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        self._watcher = self.cluster.pods.watch()
        for pod in self.cluster.pods.list():
            self._admit(pod)
        self._main = threading.Thread(target=self._run, name="sim-kubelet",
                                      daemon=True)
        self._main.start()

    def stop(self) -> None:
        self._stop.set()
        if self._watcher:
            self._watcher.stop()

    def logs(self, namespace: str, name: str, tail_lines: int = 0) -> bytes:
        return b""  # simulated pods produce no output

    # -- progress plane ------------------------------------------------------

    def suspend_heartbeats(self) -> None:
        """Stall injection: beats stop publishing while the clock keeps
        running — from the controller's view, training froze."""
        self._hb_suspended = True

    def resume_heartbeats(self) -> None:
        self._hb_suspended = False

    # -- fault injection (chaos / capacity planes) ---------------------------

    def chaos_kill(self, namespace: str, name: str) -> Optional[str]:
        key = f"{namespace}/{name}"
        try:
            pod = self.cluster.pods.get(namespace, name)
        except NotFound:
            return None
        if pod.status.phase in (PHASE_PENDING, PHASE_RUNNING):
            with self._injected_lock:
                self._injected_failures.add(key)
            self.set_phase(namespace, name, PHASE_FAILED,
                           reason="ChaosKill: injected fault")
            return "simulated"
        return None

    def fail_slice(self, slice_name: str, reason: str = "SliceFailed") -> list:
        if self.inventory is None:
            return []
        keys = set(self.inventory.fail_slice(slice_name))
        failed = []
        for key in keys:
            with self._injected_lock:
                self._injected_failures.add(key)
            ns, _, name = key.partition("/")
            self.set_phase(ns, name, PHASE_FAILED, reason=reason)
            failed.append(name)
        return failed

    def _evict_pods(self, pod_keys, reason: str) -> None:
        """Preemption/harvest executor (scheduler-registered): simulated
        pods just flip to Failed through the injected-failure path."""
        for key in pod_keys:
            with self._injected_lock:
                self._injected_failures.add(key)
            ns, _, name = key.partition("/")
            self.set_phase(ns, name, PHASE_FAILED, reason=reason)

    def _consume_injected(self, key: str) -> bool:
        with self._injected_lock:
            if key in self._injected_failures:
                self._injected_failures.discard(key)
                return True
            return False

    # -- phase writes --------------------------------------------------------

    def set_phase(self, namespace: str, name: str, phase: str,
                  reason: str = "") -> None:
        try:
            pod = self.cluster.pods.get(namespace, name)
        except NotFound:
            return
        pod.status.phase = phase
        pod.status.reason = reason
        # Sole status writer for its pods: last-write-wins, and — node
        # agent, not a controller sync path — deliberately unfenced.
        pod.metadata.resource_version = ""
        try:
            self.cluster.store.update_status("pods", pod)  # kctpu: vet-ok(fencing-token)
        except NotFound:
            pass

    def _stamp_start_mode(self, namespace: str, name: str, warm: bool) -> None:
        """Record warm/cold on the pod at admission (best-effort) so the
        goodput ledger can attribute starting time to the right bucket."""
        from ..api.labels import (
            ANNOTATION_START_MODE, START_MODE_COLD, START_MODE_WARM)

        mode = START_MODE_WARM if warm else START_MODE_COLD

        def apply(meta):
            meta.annotations[ANNOTATION_START_MODE] = mode

        try:
            self.cluster.pods.patch_meta(namespace, name, apply)
        except NotFound:
            pass

    # -- timer wheel ---------------------------------------------------------

    def _arm(self, delay_s: float, key: str, action: str) -> None:
        self._seq += 1
        heapq.heappush(self._timers,
                       (time.monotonic() + max(0.0, delay_s),
                        self._seq, key, action))

    def _admit(self, pod: Pod) -> None:
        """A pod appeared: register it and arm its first transition."""
        key = f"{pod.metadata.namespace}/{pod.metadata.name}"
        if key in self._pods:
            return
        sp = _SimPod(pod)
        self._pods[key] = sp
        if self.inventory is not None and pod_requests_tpu(pod):
            self._arm(0.0, key, _OFFER)
        else:
            self._arm(self.policy.pending_s, key, _START)

    def _run(self) -> None:
        """The loop: fire due timers, then drain watch events, sleeping
        only until the earliest timer (or a short idle tick)."""
        last_reap = time.monotonic()
        seen_gaps = getattr(self._watcher, "gaps", 0)
        while not self._stop.is_set():
            now = time.monotonic()
            while self._timers and self._timers[0][0] <= now:
                _, _, key, action = heapq.heappop(self._timers)
                sp = self._pods.get(key)
                if sp is None or sp.gone:
                    continue
                self._fire(now, key, sp, action)
            # Node-side gang reaping (two-process safety net; harmless
            # redundancy in-process) on the FakeKubelet cadence.
            if self.inventory is not None and now - last_reap > _REAP_EVERY_S:
                last_reap = now
                live = {
                    k for k, sp in self._pods.items()
                    if not sp.gone and sp.pod.status.phase
                    not in (PHASE_SUCCEEDED, PHASE_FAILED)
                }
                self.inventory.release_idle_gangs(live)
            gaps = getattr(self._watcher, "gaps", 0)
            if gaps != seen_gaps:
                seen_gaps = gaps
                for pod in self.cluster.pods.list():
                    self._admit(pod)
            timeout = 0.2
            if self._timers:
                timeout = min(timeout,
                              max(0.0, self._timers[0][0] - time.monotonic()))
            for ev in self._watcher.next_batch(max_n=512, timeout=timeout):
                self._observe(ev)

    def _observe(self, ev) -> None:
        if ev.type == ADDED:
            self._admit(ev.object)
        elif ev.type == MODIFIED:
            key = f"{ev.object.metadata.namespace}/{ev.object.metadata.name}"
            sp = self._pods.get(key)
            if sp is not None:
                sp.pod = ev.object  # keep labels/annotations/status current
                if ev.object.metadata.deletion_timestamp is not None:
                    self._mark_gone(key, sp)
        elif ev.type == DELETED:
            key = f"{ev.object.metadata.namespace}/{ev.object.metadata.name}"
            sp = self._pods.get(key)
            if sp is not None:
                self._mark_gone(key, sp)

    def _mark_gone(self, key: str, sp: _SimPod) -> None:
        """Deleted (or deleting) pod: timers for it become no-ops; the
        state entry is dropped immediately — a pod name never re-enters
        Running after deletion (generateName keeps replacements unique)."""
        sp.gone = True
        self._pods.pop(key, None)
        with self._injected_lock:
            self._injected_failures.discard(key)

    # -- the phase machine ---------------------------------------------------

    def _fire(self, now: float, key: str, sp: _SimPod, action: str) -> None:
        if action == _OFFER:
            self._fire_offer(key, sp)
        elif action == _WARMUP:
            self._arm(self.policy.pending_s, key, _START)
        elif action == _START:
            self._fire_start(now, key, sp)
        elif action == _FINISH:
            self._fire_finish(key, sp)
        elif action == _BEAT:
            self._fire_beat(now, key, sp)

    def _fire_offer(self, key: str, sp: _SimPod) -> None:
        """One gang-admission attempt (the event-driven analog of the
        threaded gate's poll loop)."""
        pod = sp.pod
        if self.inventory.offer(pod):
            if self._consume_injected(key):
                return  # failed between admission and start: stay Failed
            started = getattr(self.inventory, "pod_started", None)
            if started is not None:
                started(pod)  # releases the coordinator-first hold
            gang = pod.metadata.annotations.get(ANNOTATION_GANG_NAME, "") or key
            warm = gang in self._warm_gangs
            self._warm_gangs.add(gang)
            self._c_starts.labels("warm" if warm else "cold").inc()
            self._stamp_start_mode(pod.metadata.namespace,
                                   pod.metadata.name, warm)
            delay = (self.policy.warm_start_s if warm
                     else self.policy.cold_start_s)
            self._arm(delay, key, _WARMUP)
            return
        sp.offer_ticks += 1
        queue_info = getattr(self.inventory, "queue_info", None)
        gang = pod.metadata.annotations.get(ANNOTATION_GANG_NAME, "")
        if (queue_info is not None and gang
                and sp.offer_ticks % _REASON_EVERY == 1):
            reason = queue_info(gang)
            if reason and reason != sp.last_reason:
                sp.last_reason = reason
                self.set_phase(pod.metadata.namespace, pod.metadata.name,
                               PHASE_PENDING, reason=reason)
        self._arm(_OFFER_TICK_S, key, _OFFER)

    def _fire_start(self, now: float, key: str, sp: _SimPod) -> None:
        pod = sp.pod
        if self._consume_injected(key):
            return  # injected failure won the race: stay Failed
        self.set_phase(pod.metadata.namespace, pod.metadata.name,
                       PHASE_RUNNING)
        outcome = self.policy.outcome(pod)
        if outcome is None:
            return  # runs forever (PS): no beats, no terminal transition
        run_s = self.policy.run_s_for(pod)
        sp.finish_at = now + run_s
        sp.outcome = outcome  # policy.outcome consumed any fail_once entry
        if self.policy.heartbeat_s > 0:
            self._arm(min(self.policy.heartbeat_s, run_s), key, _BEAT)
        self._arm(run_s, key, _FINISH)

    def _fire_beat(self, now: float, key: str, sp: _SimPod) -> None:
        from ..api.core import PodProgress

        sp.step += 1
        hb = self.policy.heartbeat_s
        if not self._hb_suspended:
            try:
                self.cluster.pods.update_progress(
                    sp.pod.metadata.namespace, sp.pod.metadata.name,
                    PodProgress(
                        step=sp.step,
                        examples_per_sec=round(100.0 / hb, 3),
                        loss=round(1.0 / sp.step, 4),
                        phase="fit",
                    ))
            except APIError:
                return  # pod deleted mid-beat: no further beats
        if now + hb < sp.finish_at:
            self._arm(hb, key, _BEAT)

    def _fire_finish(self, key: str, sp: _SimPod) -> None:
        if self._consume_injected(key):
            return  # fail_slice/chaos already marked the pod Failed
        self.set_phase(sp.pod.metadata.namespace, sp.pod.metadata.name,
                       sp.outcome)
        self._pods.pop(key, None)
