"""The cluster substrate: an in-memory API server, fake kubelet, TPU inventory.

The reference is tested (when it is tested at all) against a fake clientset
over an ObjectTracker (ref: vendor/github.com/caicloud/kubeflow-clientset/
clientset/versioned/fake/clientset_generated.go:33-46) and validated manually
against a single-node cluster (ref: docs/development.md:24-33).  This package
is that substrate made first-class: a faithful in-memory API server with
CRUD + watch + resourceVersions + generateName + ownership semantics, a
kubelet that transitions pod phases (optionally by running real local
processes), and a TPU slice inventory with gang admission — so the entire
controller can be exercised end-to-end with no cluster.

The client interfaces are the seam where a real Kubernetes REST client would
plug in unchanged (SURVEY.md §7).
"""

from .store import ObjectStore, WatchEvent, Watcher, APIError, Conflict, NotFound, AlreadyExists  # noqa: F401
from .client import Cluster, PodClient, ServiceClient, TFJobClient  # noqa: F401
from .kubelet import FakeKubelet, PhasePolicy  # noqa: F401
from .simkubelet import SimKubelet  # noqa: F401
from .tpu import TPUInventory, TPUSlice  # noqa: F401
