"""REST transport: the typed clients over a real HTTP API server.

This is the other half of the clientset seam (cluster/client.py): the same
create/get/list/update/delete/watch/patch surface, spoken over HTTP to a
Kubernetes API server — kubeconfig parsing and typed CRUD+watch per the
reference's generated clients (ref: cmd/controller/main.go:47-60 builds
clients from ``-kubeconfig``/``-master``; typed TFJob client at
vendor/github.com/caicloud/kubeflow-clientset/clientset/versioned/typed/
kubeflow/v1alpha1/tfjob.go:34-154).

Paths:
- TFJobs (CRD):  /apis/kubeflow.caicloud.io/v1alpha1/namespaces/{ns}/tfjobs
  (group/version per register.go:27-31, examples/crd/crd.yml:1-12)
- Pods/Services: /api/v1/namespaces/{ns}/{pods,services}
- status subresource: .../{name}/status
- watch: ?watch=true streaming JSON lines, one {"type","object"} per line
- adoption/release: JSON merge patches on metadata
  (ref: pkg/controller/ref/service.go:126-164)

Transport: a per-host **keep-alive connection pool** (http.client over raw
sockets, checkout/return, transparent reconnect when a pooled socket went
stale while idle) rather than one fresh urllib connection per call — the
write path's slow-start batches (controller/slowstart.py) issue creates
concurrently, and without pooling every one of them would pay TCP(+TLS)
setup and the server a thread per request.  Safe verbs (GET/HEAD) get one
bounded retry on transient connection errors; mutating verbs never retry
beyond the stale-socket reconnect (the request may have been applied).

Only the standard library is used (http.client + ssl + threads): no
client-go analog to vendor.
"""

from __future__ import annotations

import calendar
import collections
import http.client
import json
import queue
import ssl
import threading
import time
import urllib.parse
from typing import Any, Callable, Dict, List, Optional, Type

from ..obs.metrics import REGISTRY

from ..api.core import EventObject, Lease, Pod, Service, TenantQuota
from ..api.meta import ObjectMeta
from ..api.tfjob import TFJob
from ..utils import locks, serde
from .store import (
    ADDED,
    AlreadyExists,
    APIError,
    BOOKMARK,
    Conflict,
    DELETED,
    Invalid,
    MODIFIED,
    NotFound,
    TooOldResourceVersion,
    WatchEvent,
)

TFJOB_GROUP = "kubeflow.caicloud.io"
TFJOB_VERSION = "v1alpha1"
TFJOB_API = f"/apis/{TFJOB_GROUP}/{TFJOB_VERSION}"
CORE_API = "/api/v1"


# ---------------------------------------------------------------------------
# kubeconfig
# ---------------------------------------------------------------------------

class KubeconfigError(APIError):
    pass


class Kubeconfig:
    """The subset of kubeconfig the controller needs: server address,
    bearer token, TLS material / insecure flag — resolved through
    current-context exactly like BuildConfigFromFlags (ref:
    cmd/controller/main.go:47-60: ``-master`` overrides the server)."""

    def __init__(self, server: str, token: str = "", insecure: bool = False,
                 ca_file: str = "", cert_file: str = "", key_file: str = ""):
        self.server = server.rstrip("/")
        self.token = token
        self.insecure = insecure
        self.ca_file = ca_file
        self.cert_file = cert_file
        self.key_file = key_file

    @staticmethod
    def load(path: str, master: str = "") -> "Kubeconfig":
        import base64
        import tempfile

        import yaml

        with open(path) as f:
            doc = yaml.safe_load(f) or {}
        ctx_name = doc.get("current-context", "")
        contexts = {c["name"]: c.get("context", {}) for c in doc.get("contexts", [])}
        clusters = {c["name"]: c.get("cluster", {}) for c in doc.get("clusters", [])}
        users = {u["name"]: u.get("user", {}) for u in doc.get("users", [])}
        ctx = contexts.get(ctx_name) or (next(iter(contexts.values())) if contexts else {})
        cluster = clusters.get(ctx.get("cluster", "")) or (
            next(iter(clusters.values())) if clusters else {})
        user = users.get(ctx.get("user", "")) or (
            next(iter(users.values())) if users else {})
        server = master or cluster.get("server", "")
        if not server:
            raise KubeconfigError(f"no server in kubeconfig {path} and no -master given")

        def materialize(data_key: str, file_key: str) -> str:
            """Inline *-data fields become temp files for ssl.*_chain APIs."""
            if user.get(file_key):
                return user[file_key]
            if cluster.get(file_key):
                return cluster[file_key]
            data = user.get(data_key) or cluster.get(data_key)
            if not data:
                return ""
            tmp = tempfile.NamedTemporaryFile(delete=False, suffix=".pem")
            tmp.write(base64.b64decode(data))
            tmp.close()
            return tmp.name

        return Kubeconfig(
            server=server,
            token=user.get("token", ""),
            insecure=bool(cluster.get("insecure-skip-tls-verify", False)),
            ca_file=materialize("certificate-authority-data", "certificate-authority"),
            cert_file=materialize("client-certificate-data", "client-certificate"),
            key_file=materialize("client-key-data", "client-key"),
        )


# ---------------------------------------------------------------------------
# Low-level HTTP
# ---------------------------------------------------------------------------

class TooManyRequests(APIError):
    """HTTP 429 from the per-tenant write throttle (apiserver write-path
    isolation): the transport already honored Retry-After with bounded
    in-flight backoff before raising; ``retry_after`` is the server's
    last hint, for callers that requeue instead of blocking."""

    def __init__(self, message: str, retry_after: float = 1.0):
        super().__init__(message)
        self.retry_after = retry_after


def _status_error(code: int, body: bytes) -> APIError:
    reason, message = "", ""
    try:
        st = json.loads(body)
        reason = st.get("reason", "")
        message = st.get("message", "")
    except (ValueError, AttributeError):
        message = body[:300].decode(errors="replace")
    if code == 404:
        return NotFound(message or "not found")
    if code == 409:
        # k8s uses 409 for both AlreadyExists and optimistic-concurrency
        # Conflict; the Status.reason disambiguates.
        if reason == "AlreadyExists":
            return AlreadyExists(message)
        return Conflict(message)
    if code == 410:
        # Gone/Expired: the watch resume RV fell out of the server's
        # watch cache — the caller must re-list.
        return TooOldResourceVersion(message or "resourceVersion too old")
    if code in (400, 422):
        return Invalid(message)
    return APIError(f"HTTP {code}: {message}")


#: Verbs that are safe to replay after a transient connection error even on
#: a FRESH socket (the server may or may not have seen the request; for
#: reads that is harmless).  Mutating verbs only get the stale-keep-alive
#: reconnect, where the idle socket died before the request was written.
_SAFE_METHODS = frozenset({"GET", "HEAD"})


class ConnectionPool:
    """Keep-alive ``http.client`` connections to ONE host.

    ``checkout()`` pops an idle connection (or dials a new one) and tells
    the caller whether the socket was reused — a reused socket may have
    been closed by the server while idle, and the transport transparently
    reconnects on that signal.  ``checkin()`` returns a healthy connection
    for reuse; at most ``maxsize`` idle connections are retained (extras
    close), which bounds server-side thread/file-descriptor load while
    letting bursts dial as wide as they need."""

    def __init__(self, server: str, ssl_context: Optional[ssl.SSLContext] = None,
                 timeout: float = 30.0, maxsize: int = 8):
        u = urllib.parse.urlsplit(server)
        self.scheme = u.scheme or "http"
        self.host = u.hostname or "localhost"
        self.port = u.port
        self.timeout = timeout
        self.maxsize = maxsize
        self._ssl = ssl_context
        self._lock = locks.named_lock("rest.conn-pool")
        self._idle: "collections.deque" = collections.deque()
        self._closed = False
        # Pool effectiveness on /metrics: dials is TCP(+TLS) setups paid,
        # reuses is setups saved.  Labelless process-wide totals (one
        # controller process talks to one API server).
        self._c_dials = REGISTRY.counter(
            "kctpu_rest_conn_dials_total",
            "New REST connections dialed (TCP/TLS setup paid)")
        self._c_reuses = REGISTRY.counter(
            "kctpu_rest_conn_reuses_total",
            "REST requests served on a pooled keep-alive connection")

    def dial(self, timeout: Optional[float] = None) -> http.client.HTTPConnection:
        """A brand-new connection, never from the idle set (watch streams
        hold their socket for up to an hour and must not starve the pool)."""
        import socket

        t = self.timeout if timeout is None else timeout
        self._c_dials.inc()
        if self.scheme == "https":
            conn = http.client.HTTPSConnection(
                self.host, self.port, timeout=t, context=self._ssl)
        else:
            conn = http.client.HTTPConnection(self.host, self.port, timeout=t)
        # Connect eagerly so TCP_NODELAY can be set: http.client writes
        # headers and body in separate segments, and on a keep-alive
        # socket Nagle + delayed ACK turns every small POST into a ~40 ms
        # stall — the dominant per-create cost until disabled.
        conn.connect()
        try:
            conn.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:  # pragma: no cover - non-TCP transports
            pass
        return conn

    def checkout(self, timeout: Optional[float] = None):
        """-> (conn, reused): ``reused`` means the socket may be stale."""
        t = self.timeout if timeout is None else timeout
        with self._lock:
            while self._idle:
                conn = self._idle.popleft()
                if conn.sock is not None:
                    try:
                        conn.sock.settimeout(t)
                    except OSError:
                        # fd already dead (closed under us while idle):
                        # drop it and keep scanning, never raise from here.
                        conn.close()
                        continue
                    self._c_reuses.inc()
                    return conn, True
                conn.close()
        return self.dial(t), False

    def checkin(self, conn: http.client.HTTPConnection) -> None:
        with self._lock:
            if (not self._closed and conn.sock is not None
                    and len(self._idle) < self.maxsize):
                conn.sock.settimeout(self.timeout)
                self._idle.append(conn)
                return
        conn.close()

    @staticmethod
    def discard(conn: http.client.HTTPConnection) -> None:
        try:
            conn.close()
        except OSError:  # pragma: no cover - close never usefully fails
            pass

    @property
    def idle_count(self) -> int:
        with self._lock:
            return len(self._idle)

    def close(self) -> None:
        with self._lock:
            self._closed = True
            conns, self._idle = list(self._idle), collections.deque()
        for c in conns:
            c.close()


class _StreamResponse:
    """A streaming response that owns its (dedicated, unpooled) connection:
    closing the stream closes the socket, which is what unblocks a watcher
    thread parked in a chunked read."""

    def __init__(self, resp: http.client.HTTPResponse,
                 conn: http.client.HTTPConnection):
        self._resp = resp
        self._conn = conn
        self.headers = resp.headers
        self.status = resp.status

    def read(self, *args):
        return self._resp.read(*args)

    def __iter__(self):
        return iter(self._resp)

    def close(self) -> None:
        try:
            self._resp.close()
        finally:
            self._conn.close()

    def __enter__(self) -> "_StreamResponse":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class RestTransport:
    def __init__(self, config: Kubeconfig, timeout: float = 30.0,
                 pool_size: int = 8, watch_resume: bool = True):
        self.config = config
        self.timeout = timeout
        # HA fencing (docs/HA.md): when set, every mutating request
        # carries the leader generation as an X-Kctpu-Fence header; the
        # server rejects tokens below its fence floor (409 Conflict), so
        # a deposed leader's in-flight REST writes cannot land.
        self.fence_provider = None  # Optional[Callable[[], Optional[int]]]
        # Multi-tenant write billing: when set, every mutating request
        # carries the caller's tenant as an X-Kctpu-Tenant header so the
        # server's per-tenant token bucket bills the right tenant even
        # when the object's namespace is not the tenant.
        self.tenant_provider = None  # Optional[Callable[[], Optional[str]]]
        self._c_throttle_waits = REGISTRY.counter(
            "kctpu_rest_throttle_waits_total",
            "429 responses honored in-flight (slept Retry-After and "
            "replayed the write)")
        # Whether watch streams reconnect with their last-seen RV
        # (RestWatcher resume) or gap on every drop.  False is the
        # pre-resumption baseline (bench.py --churn --no-resume).
        self.watch_resume = watch_resume
        self._ssl: Optional[ssl.SSLContext] = None
        if config.server.startswith("https"):
            ctx = ssl.create_default_context(
                cafile=config.ca_file or None)
            if config.insecure:
                ctx.check_hostname = False
                ctx.verify_mode = ssl.CERT_NONE
            if config.cert_file:
                ctx.load_cert_chain(config.cert_file, config.key_file or None)
            self._ssl = ctx
        self.pool = ConnectionPool(config.server, ssl_context=self._ssl,
                                   timeout=timeout, maxsize=pool_size)

    def close(self) -> None:
        self.pool.close()

    def _headers(self, data: Optional[bytes], content_type: str,
                 method: str = "GET") -> Dict[str, str]:
        h = {"Accept": "application/json"}
        if data is not None:
            h["Content-Type"] = content_type
        if self.config.token:
            h["Authorization"] = f"Bearer {self.config.token}"
        if method not in _SAFE_METHODS and self.fence_provider is not None:
            fence = self.fence_provider()
            if fence is not None:
                h["X-Kctpu-Fence"] = str(fence)
        if method not in _SAFE_METHODS and self.tenant_provider is not None:
            tenant = self.tenant_provider()
            if tenant:
                h["X-Kctpu-Tenant"] = tenant
        return h

    def _request(self, method: str, path: str,
                 body: Optional[dict] = None,
                 params: Optional[Dict[str, str]] = None,
                 content_type: str = "application/json",
                 stream: bool = False,
                 timeout: Optional[float] = None):
        url_path = path
        if params:
            url_path += "?" + urllib.parse.urlencode(params)
        url = self.config.server + url_path
        data = json.dumps(body).encode() if body is not None else None
        headers = self._headers(data, content_type, method=method)
        # One extra replay for safe verbs on transient connection errors
        # (e.g. the server dropped the connection mid-response); the
        # stale-keep-alive reconnect below is budgeted separately and is
        # bounded by the idle-set size (each loop turn consumes one).
        safe_retries = 1 if method in _SAFE_METHODS else 0
        # Per-tenant write throttle (429): honor Retry-After in-flight a
        # bounded number of times — a throttled write was NOT applied, so
        # replaying it is always safe (unlike the connection-error case).
        throttle_retries = 3
        while True:
            if stream:
                # Dedicated connection: the response owns the socket for its
                # lifetime (watches hold it for up to an hour) — never pooled.
                conn, reused = self.pool.dial(timeout), False
            else:
                conn, reused = self.pool.checkout(timeout)
            try:
                conn.request(method, url_path, body=data, headers=headers)
                resp = conn.getresponse()
            except (OSError, http.client.HTTPException) as e:
                self.pool.discard(conn)
                if reused:
                    # The keep-alive socket went stale while idle (server
                    # timeout/restart closed it before this request was
                    # processed): reconnect transparently, any verb.
                    continue
                if safe_retries > 0:
                    safe_retries -= 1
                    continue
                raise APIError(f"{method} {url}: {e!r}") from None
            if resp.status == 429:
                err_body = resp.read()
                raw_ra = resp.headers.get("Retry-After", "")
                self._done(conn, resp)
                try:
                    retry_after = max(0.05, float(raw_ra))
                except ValueError:
                    retry_after = 1.0
                if throttle_retries > 0 and not stream:
                    throttle_retries -= 1
                    self._c_throttle_waits.inc()
                    # Cap each wait so a hostile/huge hint cannot wedge a
                    # sync worker; the budget above bounds the total.
                    time.sleep(min(retry_after, 5.0))
                    continue
                raise TooManyRequests(
                    err_body[:300].decode(errors="replace")
                    or "write budget exhausted", retry_after=retry_after)
            if resp.status >= 400:
                err_body = resp.read()
                self._done(conn, resp)
                raise _status_error(resp.status, err_body)
            if stream:
                return _StreamResponse(resp, conn)
            try:
                raw = resp.read()
            except (OSError, http.client.HTTPException) as e:
                # Server lost mid-body (IncompleteRead / reset): the socket
                # is garbage either way; replay only if the verb is safe.
                self.pool.discard(conn)
                if safe_retries > 0:
                    safe_retries -= 1
                    continue
                raise APIError(f"{method} {url}: {e!r}") from None
            self._done(conn, resp)
            try:
                return json.loads(raw or b"null")
            except ValueError as e:
                raise APIError(f"{method} {url}: {e!r}") from None

    def _done(self, conn: http.client.HTTPConnection,
              resp: http.client.HTTPResponse) -> None:
        """Body fully read: pool the connection unless the server asked to
        close (or the response left undrained state on the socket)."""
        if resp.will_close or not resp.isclosed():
            self.pool.discard(conn)
        else:
            self.pool.checkin(conn)


# ---------------------------------------------------------------------------
# Wire <-> dataclass
# ---------------------------------------------------------------------------

def _parse_time(v: Any) -> Any:
    """k8s serves RFC3339 timestamps; the in-memory store (and this
    framework's metadata) uses epoch floats."""
    if isinstance(v, str):
        try:
            return calendar.timegm(time.strptime(v, "%Y-%m-%dT%H:%M:%SZ"))
        except ValueError:
            return None
    return v


def _normalize_meta(obj: dict) -> dict:
    meta = obj.get("metadata")
    if isinstance(meta, dict):
        for key in ("creationTimestamp", "deletionTimestamp"):
            if key in meta:
                t = _parse_time(meta[key])
                if t is None:
                    meta.pop(key)
                else:
                    meta[key] = t
    return obj


class RestWatcher:
    """Watch stream over HTTP chunked JSON lines; same interface as
    store.Watcher (next/stop).

    Resumable: the last resourceVersion this stream fully observed (from
    event objects and BOOKMARK checkpoints) is carried into every
    reconnect as ``?resourceVersion=``, so the server replays exactly the
    missed events and nothing is lost — no gap, no re-list.  Only when the
    server answers 410 Gone (the RV fell out of its watch cache) does the
    watcher reconnect live and bump ``gaps``, sending cache consumers
    through their re-list fallback.  ``resume=False`` restores the
    pre-resumption behavior (every reconnect is a gap) — the churn bench's
    baseline."""

    def __init__(self, transport: RestTransport, path: str,
                 params: Dict[str, str], cls: Type,
                 connect_grace: float = 5.0,
                 resource_version: Optional[str] = None,
                 resume: bool = True):
        self._transport = transport
        self._path = path
        self._params = params
        self._cls = cls
        self._resume = resume
        #: Last RV this stream is complete through; what reconnects resume
        #: from.  Seeded from a LIST's collection RV when given, refreshed
        #: by every event and bookmark.
        self.resource_version: Optional[str] = resource_version or None
        self._c_resumes = REGISTRY.counter(
            "kctpu_watch_resumes_total",
            "Watch reconnects that resumed from a resourceVersion "
            "(missed events replayed server-side; no re-list)")
        # How long pre-connect failures are retried before they become
        # fatal: long enough to tolerate a concurrently-starting server
        # even under parallel-test/CI load (process spawn can take seconds
        # there — the same reality the kubelet-exec tests budget for),
        # short enough that a down server surfaces an error in ~5 s instead
        # of each informer eating a 10 s timeout serially (advisor round-2).
        self._connect_grace = connect_grace
        # Bounded, with BACKPRESSURE rather than drop: the reader thread's
        # put blocks when the consumer lags, which stops the chunked read,
        # fills the TCP window, and pushes the overflow decision to the
        # server's bounded watcher queue — where dropping is safe, because
        # this side resumes by RV and the server watch cache replays.
        # Dropping locally would silently lose events ALREADY past
        # ``resource_version``, which no resume could recover.
        self.queue: "queue.Queue[Optional[WatchEvent]]" = queue.Queue(
            maxsize=4096)
        self._stopped = threading.Event()
        self._connected = threading.Event()
        # Incremented each time a broken stream is RE-established: events in
        # the gap are gone (the server does not replay), so consumers holding
        # a cache must re-list — client-go reflectors do the same.  The
        # informer polls this counter (informer.py:_watch_loop).
        self.gaps = 0
        self._resp = None
        # First connect outcome: set on success OR on a failure that
        # outlived the grace window, so a down server surfaces its error
        # quickly instead of being waited out.
        self._first_attempt = threading.Event()
        self._first_error: Optional[BaseException] = None
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"watch-{path}")
        self._thread.start()
        # Block until the server has accepted the watch (response headers
        # arrive only after the server registered the event stream), so an
        # object created right after watch() cannot slip into the gap.
        self._first_attempt.wait(timeout=10.0)
        if self._first_error is not None:
            self.stop()
            raise APIError(
                f"watch {path}: connect failed: {self._first_error}")

    def _run(self) -> None:
        ever_connected = False
        grace_deadline = time.monotonic() + self._connect_grace
        while not self._stopped.is_set():
            resume_rv = self.resource_version if self._resume else None
            params = dict(self._params)
            if resume_rv:
                params["resourceVersion"] = resume_rv
            try:
                self._resp = self._transport._request(
                    "GET", self._path, params=params, stream=True,
                    timeout=3600.0)
                if ever_connected:
                    if resume_rv:
                        # Resumed: the server replays everything after
                        # resume_rv, so nothing was lost in the gap — cache
                        # consumers need no re-list.
                        self._c_resumes.inc()
                    else:
                        self.gaps += 1  # after reconnect, so a re-list now is safe
                ever_connected = True
                self._connected.set()
                self._first_attempt.set()
                for raw in self._resp:
                    if self._stopped.is_set():
                        return
                    raw = raw.strip()
                    if not raw:
                        continue
                    ev = json.loads(raw)
                    if ev.get("type") == BOOKMARK:
                        rv = ((ev.get("object") or {}).get("metadata") or
                              {}).get("resourceVersion")
                        if rv:
                            self.resource_version = rv
                        continue
                    if ev.get("type") not in (ADDED, MODIFIED, DELETED):
                        continue
                    obj = serde.from_dict(self._cls, _normalize_meta(ev["object"]))
                    if obj.metadata.resource_version:
                        self.resource_version = obj.metadata.resource_version
                    self._put(WatchEvent(ev["type"], obj))
            except TooOldResourceVersion:
                # 410 Gone: the resume RV fell out of the server's watch
                # cache.  Drop it and reconnect live; that NEXT successful
                # connect has resume_rv=None and bumps `gaps` (only then is
                # it safe for the consumer to re-list — the new stream must
                # already be established or the re-list itself could race a
                # second loss).  This is the strictly-fallback re-list path.
                if self._stopped.is_set():
                    return
                self.resource_version = None
                self._connected.clear()
                continue
            except AttributeError:
                # http.client raises AttributeError when stop() closes the
                # response out from under a blocked chunked read; any OTHER
                # AttributeError is a real bug (e.g. in deserialization) and
                # must crash visibly, not loop silently.
                if self._stopped.is_set():
                    return
                raise
            except (APIError, OSError, ValueError,
                    http.client.HTTPException) as e:
                # HTTPException: IncompleteRead when the server dies
                # mid-chunk (not an OSError).
                if self._stopped.is_set():
                    return
                if not ever_connected:
                    if time.monotonic() >= grace_deadline:
                        # Never connected and the grace window is spent:
                        # report to the constructor and bail — the watcher
                        # is unusable and __init__ raises.
                        self._first_error = e
                        self._first_attempt.set()
                        return
                    time.sleep(0.2)  # server may still be starting: retry
                    continue
                self._connected.clear()
                time.sleep(0.2)  # reconnect, as client-go reflectors do

    def _put(self, ev: Optional[WatchEvent]) -> None:
        """Bounded put that stays interruptible: a stop() while the queue
        is full must still unblock the reader thread."""
        while not self._stopped.is_set():
            try:
                self.queue.put(ev, timeout=0.5)
                return
            except queue.Full:
                continue

    def next(self, timeout: Optional[float] = None) -> Optional[WatchEvent]:
        try:
            return self.queue.get(timeout=timeout)
        except queue.Empty:
            return None

    def stop(self) -> None:
        if not self._stopped.is_set():
            self._stopped.set()
            resp = self._resp
            if resp is not None:
                try:
                    resp.close()
                except OSError:
                    pass
            try:
                self.queue.put_nowait(None)
            except queue.Full:
                pass  # consumer will drain to the closed-stream end anyway


# ---------------------------------------------------------------------------
# Typed REST clients (same surface as cluster/client.py)
# ---------------------------------------------------------------------------

class _RestTypedClient:
    cls: Type = None
    plural: str = ""
    api_prefix: str = CORE_API
    api_version: str = "v1"
    kind_name: str = ""

    def __init__(self, transport: RestTransport):
        self._t = transport

    # -- paths ---------------------------------------------------------------

    def _collection(self, namespace: Optional[str]) -> str:
        if namespace:
            return f"{self.api_prefix}/namespaces/{namespace}/{self.plural}"
        return f"{self.api_prefix}/{self.plural}"

    def _item(self, namespace: str, name: str) -> str:
        return f"{self._collection(namespace)}/{name}"

    # -- serialization -------------------------------------------------------

    def _to_wire(self, obj) -> dict:
        d = serde.to_dict(obj)
        d["apiVersion"] = self.api_version
        d["kind"] = self.kind_name
        return d

    def _from_wire(self, d: dict):
        return serde.from_dict(self.cls, _normalize_meta(d))

    # -- CRUD ----------------------------------------------------------------

    def create(self, obj):
        ns = obj.metadata.namespace or "default"
        out = self._t._request("POST", self._collection(ns), body=self._to_wire(obj))
        return self._from_wire(out)

    def get(self, namespace: str, name: str):
        return self._from_wire(self._t._request("GET", self._item(namespace, name)))

    def list(self, namespace: Optional[str] = None,
             selector: Optional[Dict[str, str]] = None):
        return self.list_with_rv(namespace, selector)[0]

    def list_with_rv(self, namespace: Optional[str] = None,
                     selector: Optional[Dict[str, str]] = None):
        """-> (items, collection resourceVersion): the server's
        ListMeta.resourceVersion, a resume point for ``watch()``."""
        params = {}
        if selector:
            params["labelSelector"] = ",".join(f"{k}={v}" for k, v in selector.items())
        out = self._t._request("GET", self._collection(namespace), params=params or None)
        rv = ((out.get("metadata") or {}).get("resourceVersion") or "")
        return [self._from_wire(item) for item in out.get("items", [])], rv

    def update(self, obj):
        out = self._t._request(
            "PUT", self._item(obj.metadata.namespace, obj.metadata.name),
            body=self._to_wire(obj))
        return self._from_wire(out)

    def delete(self, namespace: str, name: str):
        self._t._request("DELETE", self._item(namespace, name))

    def watch(self, namespace: Optional[str] = None,
              resource_version: Optional[str] = None) -> RestWatcher:
        return RestWatcher(self._t, self._collection(namespace),
                           {"watch": "true"}, self.cls,
                           resource_version=resource_version,
                           resume=self._t.watch_resume)

    def patch(self, namespace: str, name: str, body: dict):
        """Arbitrary object patch as an RFC 7386 merge patch — the
        PatchService analog (ref: pkg/controller/control/service.go:50-53)
        for every kind: ``patch(ns, n, {"spec": {...}})`` mutates just
        those fields server-side."""
        out = self._t._request(
            "PATCH", self._item(namespace, name), body=body,
            content_type="application/merge-patch+json")
        return self._from_wire(out)

    def patch_meta(self, namespace: str, name: str,
                   fn: Callable[[ObjectMeta], None]):
        """Read-modify-write expressed as a JSON merge patch on metadata —
        the wire form the reference uses for adoption/release
        (ref: pkg/controller/ref/service.go:126-164).  Lists
        (ownerReferences, finalizers) replace wholesale; label/annotation
        maps merge per-key, so keys ``fn`` removed are expressed as RFC
        7386 nulls."""
        current = self.get(namespace, name)
        meta = current.metadata
        before_labels = dict(meta.labels)
        before_annotations = dict(meta.annotations)
        fn(meta)

        def map_patch(before: dict, after: dict) -> dict:
            out = {k: v for k, v in after.items() if before.get(k) != v}
            out.update({k: None for k in before if k not in after})
            return out

        meta_patch = {
            "labels": map_patch(before_labels, dict(meta.labels)),
            "annotations": map_patch(before_annotations, dict(meta.annotations)),
            "ownerReferences": serde.to_dict(meta.owner_references) or [],
            "finalizers": list(meta.finalizers),
        }
        out = self._t._request(
            "PATCH", self._item(namespace, name),
            body={"metadata": meta_patch},
            content_type="application/merge-patch+json")
        return self._from_wire(out)


class RestTFJobClient(_RestTypedClient):
    cls = TFJob
    plural = "tfjobs"
    api_prefix = TFJOB_API
    api_version = f"{TFJOB_GROUP}/{TFJOB_VERSION}"
    kind_name = "TFJob"

    def update_status(self, job: TFJob) -> TFJob:
        out = self._t._request(
            "PUT", self._item(job.metadata.namespace, job.metadata.name) + "/status",
            body=self._to_wire(job))
        return self._from_wire(out)


class RestPodClient(_RestTypedClient):
    cls = Pod
    plural = "pods"
    kind_name = "Pod"

    def list_pods(self, namespace: Optional[str] = None) -> List[Pod]:
        return self.list(namespace)

    def update_progress(self, namespace: str, name: str, progress) -> Pod:
        """PUT .../pods/{name}/progress — the training-plane heartbeat
        subresource (last-write-wins server-side; only ``.status.progress``
        is applied)."""
        out = self._t._request(
            "PUT", self._item(namespace, name) + "/progress",
            body=serde.to_dict(progress))
        return self._from_wire(out)

    def read_log(self, namespace: str, name: str, tail_lines: int = 0) -> str:
        """GET .../pods/{name}/log — combined stdout+stderr, kubectl-logs
        style (served by the API server's attached node agent).
        ``tail_lines`` > 0 maps to the k8s ``tailLines`` param: the kubelet
        serves only the last N lines, tail-reading files instead of
        shipping whole logs."""
        params = {"tailLines": str(tail_lines)} if tail_lines > 0 else None
        resp = self._t._request(
            "GET", self._item(namespace, name) + "/log", params=params,
            stream=True)
        try:
            with resp:
                return resp.read().decode(errors="replace")
        except (OSError, http.client.HTTPException) as e:
            raise APIError(f"reading log of {namespace}/{name}: {e!r}") from None

    def mark_deleting(self, namespace: str, name: str) -> Pod:
        """Graceful pod deletion: the API server stamps deletionTimestamp
        and the kubelet finishes — a plain DELETE on the wire."""
        self._t._request("DELETE", self._item(namespace, name))
        try:
            return self.get(namespace, name)
        except NotFound:
            # Server deleted immediately (no grace): synthesize the state
            # callers observe through the in-memory path.
            pod = Pod()
            pod.metadata.namespace = namespace
            pod.metadata.name = name
            pod.metadata.deletion_timestamp = time.time()
            return pod


class RestServiceClient(_RestTypedClient):
    cls = Service
    plural = "services"
    kind_name = "Service"

    def list_services(self, namespace: Optional[str] = None) -> List[Service]:
        return self.list(namespace)


class RestEventClient(_RestTypedClient):
    cls = EventObject
    plural = "events"
    kind_name = "Event"


class RestLeaseClient(_RestTypedClient):
    cls = Lease
    plural = "leases"
    api_version = "coordination.k8s.io/v1"
    kind_name = "Lease"


class RestTenantQuotaClient(_RestTypedClient):
    cls = TenantQuota
    plural = "tenantquotas"
    api_version = f"{TFJOB_GROUP}/{TFJOB_VERSION}"
    kind_name = "TenantQuota"


class RestCluster:
    """Drop-in for cluster.Cluster backed by HTTP — what ``-kubeconfig``
    selects in the CLI.  No ``.store``: there is no in-process substrate,
    the API server is authoritative."""

    def __init__(self, config: Kubeconfig, pool_size: int = 8,
                 watch_resume: bool = True):
        self.config = config
        self.transport = RestTransport(config, pool_size=pool_size,
                                       watch_resume=watch_resume)
        self.tfjobs = RestTFJobClient(self.transport)
        self.pods = RestPodClient(self.transport)
        self.services = RestServiceClient(self.transport)
        self.events = RestEventClient(self.transport)
        self.leases = RestLeaseClient(self.transport)
        self.tenantquotas = RestTenantQuotaClient(self.transport)

    def set_tenant_provider(self, tp) -> None:
        """Stamp every write from this cluster handle with the given
        tenant provider (() -> tenant str) so the server's per-tenant
        write throttle bills the right principal."""
        self.transport.tenant_provider = tp

    def set_fence_provider(self, fp) -> None:
        """Stamp every write from this cluster handle with the given
        fence token provider (e.g. ``LeaseManager.token``) — the REST
        half of the Cluster.set_fence_provider contract."""
        self.transport.fence_provider = fp

    def close(self) -> None:
        """Release pooled keep-alive connections (idempotent)."""
        self.transport.close()

    # -- observability surface (non-k8s paths on the same server) -----------

    def metrics_text(self) -> str:
        """GET /metrics — raw Prometheus text exposition (what a scraper
        sees; served by the in-process API server's obs registry)."""
        resp = self.transport._request("GET", "/metrics", stream=True)
        try:
            with resp:
                return resp.read().decode(errors="replace")
        except (OSError, http.client.HTTPException) as e:
            raise APIError(f"reading /metrics: {e!r}") from None

    def trace_events(self) -> dict:
        """GET /debug/traces — the server process's span ring buffer as a
        Chrome trace_event JSON document."""
        return self.transport._request("GET", "/debug/traces")

    def debug_query(self, params: Dict[str, str]) -> dict:
        """GET /debug/query — windowed queries over the server process's
        retained-series store (obs/tsdb.py)."""
        from urllib.parse import urlencode

        return self.transport._request(
            "GET", f"/debug/query?{urlencode(params)}")

    def debug_slos(self) -> dict:
        """GET /debug/slos — SLO objectives + live burn-alert states."""
        return self.transport._request("GET", "/debug/slos")

    @staticmethod
    def from_flags(kubeconfig: str, master: str = "") -> "RestCluster":
        """BuildConfigFromFlags parity (ref: cmd/controller/main.go:47-60)."""
        if kubeconfig:
            return RestCluster(Kubeconfig.load(kubeconfig, master=master))
        if master:
            return RestCluster(Kubeconfig(server=master))
        raise KubeconfigError("one of -kubeconfig/-master is required")
