"""Typed clients over the object store — the clientset seam.

Functional equivalent of the generated typed clients
(ref: vendor/github.com/caicloud/kubeflow-clientset/clientset/versioned/
typed/kubeflow/v1alpha1/tfjob.go:34-154 for TFJobs; client-go core/v1 for
pods/services).  A real REST implementation of these three classes is all it
would take to run the controller against a live API server.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ..api.core import Pod, Service
from ..api.tfjob import TFJob
from .store import ObjectStore, Watcher

TFJOBS = "tfjobs"
PODS = "pods"
SERVICES = "services"
EVENTS = "events"


class _TypedClient:
    kind: str = ""

    def __init__(self, store: ObjectStore):
        self._store = store

    def create(self, obj):
        return self._store.create(self.kind, obj)

    def get(self, namespace: str, name: str):
        return self._store.get(self.kind, namespace, name)

    def list(self, namespace: Optional[str] = None, selector: Optional[Dict[str, str]] = None):
        return self._store.list(self.kind, namespace, selector)

    def list_with_rv(self, namespace: Optional[str] = None,
                     selector: Optional[Dict[str, str]] = None):
        """-> (items, collection resourceVersion): every LIST is a watch
        resume point (ListMeta.resourceVersion semantics)."""
        return self._store.list_with_rv(self.kind, namespace, selector)

    def update(self, obj):
        return self._store.update(self.kind, obj)

    def delete(self, namespace: str, name: str):
        return self._store.delete(self.kind, namespace, name)

    def watch(self, namespace: Optional[str] = None,
              resource_version: Optional[str] = None) -> Watcher:
        return self._store.watch(self.kind, namespace,
                                 since_rv=resource_version or None)

    def patch_meta(self, namespace: str, name: str, fn):
        return self._store.patch_meta(self.kind, namespace, name, fn)

    def patch(self, namespace: str, name: str, body: Dict):
        """Arbitrary object patch (RFC 7386 merge) — PatchService analog."""
        return self._store.patch(self.kind, namespace, name, body)


class TFJobClient(_TypedClient):
    kind = TFJOBS

    def update_status(self, job: TFJob) -> TFJob:
        return self._store.update_status(self.kind, job)


class PodClient(_TypedClient):
    kind = PODS

    def list_pods(self, namespace: Optional[str] = None) -> List[Pod]:
        return self.list(namespace)

    def mark_deleting(self, namespace: str, name: str) -> Pod:
        return self._store.mark_deleting(self.kind, namespace, name)

    def update_progress(self, namespace: str, name: str, progress) -> Pod:
        """Write the pod's training-plane heartbeat (progress subresource:
        last-write-wins, only ``.status.progress`` is applied)."""
        return self._store.update_progress(self.kind, namespace, name, progress)


class ServiceClient(_TypedClient):
    kind = SERVICES

    def list_services(self, namespace: Optional[str] = None) -> List[Service]:
        return self.list(namespace)


class EventClient(_TypedClient):
    kind = EVENTS


class Cluster:
    """One handle bundling the store and its typed clients (the analog of
    building both clientsets in cmd/controller/main.go:52-60)."""

    def __init__(self, store: Optional[ObjectStore] = None):
        self.store = store or ObjectStore()
        self.tfjobs = TFJobClient(self.store)
        self.pods = PodClient(self.store)
        self.services = ServiceClient(self.store)
        self.events = EventClient(self.store)
