"""Typed clients over the object store — the clientset seam.

Functional equivalent of the generated typed clients
(ref: vendor/github.com/caicloud/kubeflow-clientset/clientset/versioned/
typed/kubeflow/v1alpha1/tfjob.go:34-154 for TFJobs; client-go core/v1 for
pods/services).  A real REST implementation of these three classes is all it
would take to run the controller against a live API server.

HA fencing (docs/HA.md): every WRITE through a typed client carries the
cluster's current fencing token (``fence=``) — the lease generation of the
leader this client acts for, or None for unfenced writers (node agents,
workloads, tests).  The plumbing is mandatory (``kctpu vet`` rule
``fencing-token``): a store write without a fence decision is how a
deposed leader corrupts state after failover.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

from ..api.core import Lease, Pod, Service
from ..api.tfjob import TFJob
from .store import LEASES_KIND, ObjectStore, Watcher

TFJOBS = "tfjobs"
PODS = "pods"
SERVICES = "services"
EVENTS = "events"
LEASES = LEASES_KIND
TENANTQUOTAS = "tenantquotas"

#: Fence provider signature: () -> Optional[int] (the lease generation).
FenceProvider = Callable[[], Optional[int]]


def _unfenced() -> Optional[int]:
    return None


class _TypedClient:
    kind: str = ""

    def __init__(self, store: ObjectStore,
                 fence: Optional[FenceProvider] = None):
        self._store = store
        self._fence = fence or _unfenced

    def create(self, obj):
        return self._store.create(self.kind, obj, fence=self._fence())

    def get(self, namespace: str, name: str):
        return self._store.get(self.kind, namespace, name)

    def list(self, namespace: Optional[str] = None, selector: Optional[Dict[str, str]] = None):
        return self._store.list(self.kind, namespace, selector)

    def list_with_rv(self, namespace: Optional[str] = None,
                     selector: Optional[Dict[str, str]] = None):
        """-> (items, collection resourceVersion): every LIST is a watch
        resume point (ListMeta.resourceVersion semantics)."""
        return self._store.list_with_rv(self.kind, namespace, selector)

    def update(self, obj):
        return self._store.update(self.kind, obj, fence=self._fence())

    def delete(self, namespace: str, name: str):
        return self._store.delete(self.kind, namespace, name,
                                  fence=self._fence())

    def watch(self, namespace: Optional[str] = None,
              resource_version: Optional[str] = None) -> Watcher:
        return self._store.watch(self.kind, namespace,
                                 since_rv=resource_version or None)

    def patch_meta(self, namespace: str, name: str, fn):
        return self._store.patch_meta(self.kind, namespace, name, fn,
                                      fence=self._fence())

    def patch(self, namespace: str, name: str, body: Dict):
        """Arbitrary object patch (RFC 7386 merge) — PatchService analog."""
        return self._store.patch(self.kind, namespace, name, body,
                                 fence=self._fence())


class TFJobClient(_TypedClient):
    kind = TFJOBS

    def update_status(self, job: TFJob) -> TFJob:
        return self._store.update_status(self.kind, job, fence=self._fence())


class PodClient(_TypedClient):
    kind = PODS

    def list_pods(self, namespace: Optional[str] = None) -> List[Pod]:
        return self.list(namespace)

    def mark_deleting(self, namespace: str, name: str) -> Pod:
        return self._store.mark_deleting(self.kind, namespace, name,
                                         fence=self._fence())

    def update_progress(self, namespace: str, name: str, progress) -> Pod:
        """Write the pod's training-plane heartbeat (progress subresource:
        last-write-wins, only ``.status.progress`` is applied)."""
        return self._store.update_progress(self.kind, namespace, name,
                                           progress, fence=self._fence())


class ServiceClient(_TypedClient):
    kind = SERVICES

    def list_services(self, namespace: Optional[str] = None) -> List[Service]:
        return self.list(namespace)


class EventClient(_TypedClient):
    kind = EVENTS


class LeaseClient(_TypedClient):
    """coordination.k8s.io Leases (ha/lease.py).  Lease writes are exempt
    from the fence check server-side — the lease IS the fencing
    authority — so the provider plumbed here is inert for this kind."""

    kind = LEASES

    def get(self, namespace: str, name: str) -> Lease:
        return self._store.get(self.kind, namespace, name)


class TenantQuotaClient(_TypedClient):
    """TenantQuota fair-share contracts (api/core.py), stored/watched
    like leases: the scheduler's tenant ledger watches this collection
    and re-keys its share heap on every spec change."""

    kind = TENANTQUOTAS


class Cluster:
    """One handle bundling the store and its typed clients (the analog of
    building both clientsets in cmd/controller/main.go:52-60).

    ``fence_provider`` (settable later via :meth:`set_fence_provider`,
    e.g. to a :meth:`LeaseManager.token <..ha.lease.LeaseManager.token>`
    bound method) stamps every write issued through this handle with the
    leader generation it acts for."""

    def __init__(self, store: Optional[ObjectStore] = None,
                 fence_provider: Optional[FenceProvider] = None):
        self.store = store or ObjectStore()
        self._fence_provider = fence_provider
        self.tfjobs = TFJobClient(self.store, self._fence)
        self.pods = PodClient(self.store, self._fence)
        self.services = ServiceClient(self.store, self._fence)
        self.events = EventClient(self.store, self._fence)
        self.leases = LeaseClient(self.store, self._fence)
        self.tenantquotas = TenantQuotaClient(self.store, self._fence)

    def _fence(self) -> Optional[int]:
        fp = self._fence_provider
        return fp() if fp is not None else None

    def set_fence_provider(self, fp: Optional[FenceProvider]) -> None:
        self._fence_provider = fp
