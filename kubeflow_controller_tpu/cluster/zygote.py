"""Warm-start zygote: forkserver for executed pods.

Pod cold-start on a small host is dominated by interpreter + framework
import (~3s per process here — the analog of image pull + container start,
which the reference's cluster pays per pod too: ~4s spread for 6 pods,
ref: docs/design_doc.md:137-149).  The kubelet amortizes it by keeping ONE
warm process that has pre-imported the heavy modules and forks each pod's
process from it — the multiprocessing-forkserver pattern.

The zygote stays **single-threaded** (select on stdin + WNOHANG reaping)
so forking is safe, and never initializes a jax backend — children pick
their own platform (the workloads' ``--platform`` flag runs
``jax.config.update`` post-fork).

Protocol (JSON lines over stdin/stdout):
  -> {"id": 1, "argv": ["-m", "mod", ...], "env": {...}, "cwd": "...",
      "stdout": "/path", "stderr": "/path"}
  <- {"id": 1, "event": "started", "pid": 123}
  -> {"kill": 1}
  <- {"id": 1, "event": "exit", "code": 0}
"""

from __future__ import annotations

import json
import os
import runpy
import select
import signal
import sys
import time
from typing import Dict


PREIMPORT = (
    "jax",
    "jax.numpy",
    "optax",
    "numpy",
    "kubeflow_controller_tpu.models",
    "kubeflow_controller_tpu.workloads.compile_cache",
    "kubeflow_controller_tpu.workloads.data",
    "kubeflow_controller_tpu.workloads.progress",
    "kubeflow_controller_tpu.workloads.runtime",
    "kubeflow_controller_tpu.workloads.trainer",
    "kubeflow_controller_tpu.workloads.mnist_local",
    "kubeflow_controller_tpu.workloads.mnist_dist",
    "kubeflow_controller_tpu.workloads.llama_pretrain",
    "kubeflow_controller_tpu.workloads.flax_mnist",
    "kubeflow_controller_tpu.workloads.cifar_allreduce",
    "kubeflow_controller_tpu.models.vision",
)


def _child(req: dict) -> None:
    """Runs in the forked child: become the pod process."""
    try:
        os.setsid()  # own process group so kills don't hit the zygote
        # Drop the protocol pipe fds: holding the request pipe (fd 0) or the
        # dup'd reply pipe open would keep the kubelet's reader alive after
        # the zygote dies, masking its death while any child runs.
        try:
            devnull = os.open(os.devnull, os.O_RDONLY)
            os.dup2(devnull, 0)
            os.close(devnull)
        except OSError:
            pass
        if _REPLY_FD[0] is not None:
            try:
                os.close(_REPLY_FD[0])
            except OSError:
                pass
        for stream, path, mode in (
            (1, req.get("stdout"), os.O_WRONLY | os.O_CREAT | os.O_APPEND),
            (2, req.get("stderr"), os.O_WRONLY | os.O_CREAT | os.O_APPEND),
        ):
            if path:
                fd = os.open(path, mode, 0o644)
                os.dup2(fd, stream)
                os.close(fd)
        env = req.get("env") or {}
        # Replace, not merge: cold-start pods get Popen(env=...) verbatim,
        # so warm-forked pods must not inherit zygote-only vars either.
        os.environ.clear()
        os.environ.update(env)
        if req.get("cwd"):
            os.chdir(req["cwd"])
        argv = list(req["argv"])
        if argv[:1] == ["-m"]:
            module, args = argv[1], argv[2:]
        else:  # tolerate a leading interpreter path
            i = argv.index("-m")
            module, args = argv[i + 1], argv[i + 2:]
        sys.argv = [module] + args
        try:
            runpy.run_module(module, run_name="__main__", alter_sys=True)
            code = 0
        except SystemExit as e:
            code = int(e.code or 0) if not isinstance(e.code, str) else 1
    except BaseException:  # noqa: BLE001 - report, never return to zygote loop
        import traceback

        traceback.print_exc()
        code = 1
    finally:
        # os._exit skips atexit, so the tracer's $KCTPU_TRACE_DIR dump
        # (obs/trace.py) would be lost for every warm-forked pod.  Dump
        # explicitly — but only when the workload actually imported the
        # tracer; don't pull obs into processes that never traced.
        tr = sys.modules.get("kubeflow_controller_tpu.obs.trace")
        if tr is not None:
            try:
                tr.dump_to_env_dir()
            except Exception:  # noqa: BLE001 - never block the exit path
                pass
        sys.stdout.flush()
        sys.stderr.flush()
        os._exit(code)


# Reply-pipe fd, stashed so forked children can close it (see _child).
_REPLY_FD = [None]


def _kill_group(pid: int, sig: int = signal.SIGTERM) -> None:
    """Signal a child's process group, falling back to the pid itself if
    the group does not exist yet (fork->setsid race on immediate deletes)."""
    try:
        os.killpg(pid, sig)
    except ProcessLookupError:
        try:
            os.kill(pid, sig)
        except ProcessLookupError:
            pass


# SIGTERM -> SIGKILL escalation grace.  A multi-process jax.distributed
# worker IGNORES SIGTERM (XLA's coordination runtime installs its own
# handlers), so a HEALTHY gang torn down by the controller — the elastic
# plane's re-shard transitions do exactly this — would otherwise survive
# as an orphan, keep training, and keep writing checkpoints over the
# replacement generation's.  Short on purpose: these pods have no
# graceful-termination contract, and a torn mid-save checkpoint is
# already handled by the restore fallback.
KILL_ESCALATE_S = 0.5


def main() -> int:
    for mod in PREIMPORT:
        try:
            __import__(mod)
        except Exception:  # pragma: no cover - optional preloads
            pass
    reply_fd = os.dup(1)
    _REPLY_FD[0] = reply_fd
    out = os.fdopen(reply_fd, "w", buffering=1)
    # Reserve fd 1 for the protocol; anything the zygote itself prints goes
    # to stderr instead.
    os.dup2(2, 1)

    out.write(json.dumps({"event": "ready"}) + "\n")
    pids: Dict[int, int] = {}  # id -> pid
    pending_kills: Dict[int, float] = {}  # pid -> SIGKILL deadline
    buf = b""
    stdin_fd = sys.stdin.fileno()
    while True:
        ready, _, _ = select.select([stdin_fd], [], [], 0.05)
        if ready:
            chunk = os.read(stdin_fd, 65536)
            if not chunk:
                break  # kubelet went away: shut down
            buf += chunk
            while b"\n" in buf:
                line, buf = buf.split(b"\n", 1)
                if not line.strip():
                    continue
                req = json.loads(line)
                if "kill" in req:
                    pid = pids.get(req["kill"])
                    if pid:
                        _kill_group(pid)
                        pending_kills[pid] = time.time() + KILL_ESCALATE_S
                    continue
                pid = os.fork()
                if pid == 0:
                    _child(req)  # never returns
                pids[req["id"]] = pid
                out.write(json.dumps(
                    {"id": req["id"], "event": "started", "pid": pid}) + "\n")
        # Reap exited children.
        for rid, pid in list(pids.items()):
            done, status = os.waitpid(pid, os.WNOHANG)
            if done:
                del pids[rid]
                pending_kills.pop(pid, None)
                out.write(json.dumps({
                    "id": rid, "event": "exit",
                    "code": os.waitstatus_to_exitcode(status),
                }) + "\n")
        # Escalate kills that SIGTERM did not take (see KILL_ESCALATE_S).
        now = time.time()
        for pid, deadline in list(pending_kills.items()):
            if pid not in pids.values():
                pending_kills.pop(pid, None)
            elif now >= deadline:
                _kill_group(pid, signal.SIGKILL)
                pending_kills.pop(pid, None)
    for pid in pids.values():
        _kill_group(pid)
    deadline = time.time() + 3
    for rid, pid in list(pids.items()):
        while time.time() < deadline:
            if os.waitpid(pid, os.WNOHANG)[0]:
                pids.pop(rid, None)
                break
            time.sleep(0.02)
    for pid in pids.values():
        # SIGTERM-immune leftovers (multi-process jax gangs): no orphans.
        _kill_group(pid, signal.SIGKILL)
    return 0


if __name__ == "__main__":
    sys.exit(main())
