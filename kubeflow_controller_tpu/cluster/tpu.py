"""TPU slice inventory with gang admission — the fake platform boundary.

Net-new capability (SURVEY.md §7 "hard parts: gang semantics for TPU
slices"): all pods of one slice are admitted atomically onto one free slice
or not at all, and the whole slice is a single failure domain.  The real
counterpart is GKE's TPU slice scheduling; tests fake it here the same way
the reference fakes its cluster (SURVEY.md §4).

Topology (multi-slice placement): slices live in a physical adjacency
structure — a *DCN domain* (``TPUSlice.pod_id``: the pod/superblock whose
slices share a data-center-network aggregation layer).  Cross-slice
collectives pay per-domain setup and per-step latency, so a gang spanning
fewer domains rendezvouses and steps faster.  ``_find_free_slices`` scores
candidate sets by :func:`adjacency_score` (1.0 = one domain, 0.0 = every
slice its own domain) and binds the set spanning the fewest domains;
``release_slices`` keeps the surviving set contiguous by releasing the
slices that break the fewest domains (and never the coordinator's).  A
slice with no ``pod_id`` is its own domain — the flat pre-topology
behavior, bit-identical to first-fit.
"""

from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional

from ..api.core import Pod, RESOURCE_TPU
from ..utils import locks
from ..api.labels import (
    ANNOTATION_ACCELERATOR,
    ANNOTATION_GANG_NAME,
    ANNOTATION_GANG_SIZE,
    ANNOTATION_NUM_SLICES,
)


@dataclass
class TPUSlice:
    name: str
    accelerator_type: str = "v5e-8"
    num_hosts: int = 2
    chips_per_host: int = 4
    # gang currently bound to this slice ("" = free).
    bound_gang: str = ""
    # False once the slice has failed: never admits another gang (the fake
    # analog of a cordoned node pool).
    healthy: bool = True
    # Wall-clock of the current binding (0 = free); feeds the utilization
    # accounting the contention bench and kctpu_slice_utilization read.
    bound_at: float = 0.0
    # Topology coordinates: the pod/superblock whose slices share a DCN
    # aggregation layer ("" = no topology info: the slice is its own
    # domain), and the slice's position within it.
    pod_id: str = ""
    pod_pos: int = 0


def dcn_domain(s: TPUSlice) -> str:
    """The DCN adjacency domain a slice belongs to.  A slice without
    topology coordinates is its own domain, which makes every adjacency
    computation degenerate to the flat pre-topology behavior."""
    return s.pod_id or s.name


def adjacency_score(n_slices: int, n_domains: int) -> float:
    """1.0 when the gang sits in a single DCN domain, 0.0 when every
    slice is in its own; linear in the number of domain crossings."""
    if n_slices <= 1:
        return 1.0
    return (n_slices - n_domains) / (n_slices - 1)


@dataclass
class _Gang:
    name: str
    size: int
    accelerator_type: str
    num_slices: int = 1
    # "namespace/name" -> pod.  Namespace-qualified so a same-named pod in
    # another namespace can neither mask a dead gang's idleness nor be
    # killed by a foreign slice failure.
    pods: Dict[str, Pod] = field(default_factory=dict)
    slice_names: List[str] = field(default_factory=list)  # set once admitted

    @property
    def slice_name(self) -> str:
        """First bound slice ("" before admission) — single-slice view."""
        return self.slice_names[0] if self.slice_names else ""


def pod_requests_tpu(pod: Pod) -> bool:
    return any(
        RESOURCE_TPU in c.resources.requests or RESOURCE_TPU in c.resources.limits
        for c in pod.spec.containers
    )


class TPUInventory:
    """Tracks slices and gangs; admits gangs all-or-nothing."""

    def __init__(self, slices: Optional[List[TPUSlice]] = None,
                 placement: str = "adjacency", seed: int = 0):
        if placement not in ("adjacency", "random"):
            raise ValueError(f"unknown placement mode {placement!r}")
        # "adjacency" (default) picks free-slice sets spanning the fewest
        # DCN domains; "random" shuffles the candidates — the placement
        # baseline the multislice bench compares against.
        self._placement = placement
        self._rng = random.Random(seed)
        self._lock = locks.named_lock("tpu.inventory")
        self.slices: Dict[str, TPUSlice] = {s.name: s for s in (slices or [])}
        # Free-capacity index: accelerator type -> count of free healthy
        # slices, maintained on every bind/unbind/failure.  The scheduler
        # (and every gated pod's poll loop) asks "is there capacity" far
        # more often than it binds — those queries must be O(1), not a
        # scan of the slice table.
        self._free_counts: Dict[str, int] = {}
        for s in self.slices.values():
            if s.healthy and not s.bound_gang:
                self._free_counts[s.accelerator_type] = (
                    self._free_counts.get(s.accelerator_type, 0) + 1)
        self._gangs: Dict[str, _Gang] = {}
        # Gangs seen idle by the last release_idle_gangs scan (two-scan
        # confirmation guards the snapshot race — see release_idle_gangs).
        self._idle_candidates: set = set()
        # Bumped on every bind/release/failure: the cheap "capacity may
        # have changed" signal the gang scheduler polls instead of
        # re-running its admission pass on every offer.
        self._version = 0
        # Accumulated slice-busy seconds of COMPLETED bindings; in-flight
        # bindings are added at read time (busy_seconds).
        self._busy_s = 0.0

    @property
    def version(self) -> int:
        return self._version

    def add_slice(self, s: TPUSlice) -> None:
        with self._lock:
            old = self.slices.get(s.name)
            if old is not None and old.healthy and not old.bound_gang:
                self._free_counts[old.accelerator_type] -= 1
            self.slices[s.name] = s
            if s.healthy and not s.bound_gang:
                self._free_counts[s.accelerator_type] = (
                    self._free_counts.get(s.accelerator_type, 0) + 1)
            self._version += 1

    def offer(self, pod: Pod) -> bool:
        """Offer a TPU pod for scheduling.  Returns True iff the pod's gang is
        (now) admitted onto its slices — i.e. the pod may leave Pending.

        A gang spanning N slices (multislice) is admitted all-or-nothing
        onto N free healthy slices.  Non-gang TPU pods (no gang annotation)
        are admitted alone onto any free slice."""
        ann = pod.metadata.annotations
        gang_name = ann.get(ANNOTATION_GANG_NAME, "")
        accel = ann.get(ANNOTATION_ACCELERATOR, "")
        with self._lock:
            if not gang_name:
                return self._find_free_slices(accel, 1) is not None
            size = int(ann.get(ANNOTATION_GANG_SIZE, "1"))
            n_slices = int(ann.get(ANNOTATION_NUM_SLICES, "1") or "1")
            gang = self._gangs.setdefault(
                gang_name, _Gang(gang_name, size, accel, num_slices=n_slices))
            gang.pods[f"{pod.metadata.namespace}/{pod.metadata.name}"] = pod
            gang.size = size  # annotation is authoritative across widths
            if gang.slice_names:
                if n_slices > len(gang.slice_names):
                    # Elastic re-expansion: the new generation spans more
                    # slices than the (harvested/degraded) binding — grow
                    # it in place, all-or-nothing, before anyone starts.
                    extra = self._find_free_slices(
                        accel, n_slices - len(gang.slice_names),
                        prefer_domains=self._gang_domains_locked(gang))
                    if extra is None:
                        return False  # capacity not back yet: hold
                    self._bind_locked(gang, extra)
                    gang.num_slices = len(gang.slice_names)
                return True  # already admitted; late pod joins
            if len(gang.pods) < gang.size:
                return False  # gang incomplete: hold everything
            found = self._find_free_slices(accel, gang.num_slices)
            if found is None:
                return False  # complete but no capacity: hold (no partial admission)
            self._bind_locked(gang, found)
            return True

    def _bind_locked(self, gang: _Gang, found: List[TPUSlice]) -> None:
        now = time.time()
        for sl in found:
            sl.bound_gang = gang.name
            sl.bound_at = now
            self._free_counts[sl.accelerator_type] -= 1
        # Append (fresh binds start from an empty list): elastic
        # re-expansion grows an admitted gang's binding in place.
        gang.slice_names = gang.slice_names + [sl.name for sl in found]
        self._version += 1

    def _unbind_locked(self, sl: TPUSlice) -> None:
        if sl.bound_at:
            self._busy_s += max(0.0, time.time() - sl.bound_at)
        if sl.bound_gang and sl.healthy:
            self._free_counts[sl.accelerator_type] = (
                self._free_counts.get(sl.accelerator_type, 0) + 1)
        sl.bound_gang = ""
        sl.bound_at = 0.0
        self._version += 1

    # -- scheduler front door ------------------------------------------------

    def bind_gang(self, gang_name: str, accelerator_type: str,
                  n_slices: int = 1, size: int = 0,
                  pods: Optional[Dict[str, Pod]] = None) -> Optional[List[str]]:
        """Atomically bind ``n_slices`` free healthy slices to the gang, or
        None if fewer exist — the admission primitive the gang scheduler
        drives (``offer`` keeps the first-come baseline semantics around
        it).  ``pods`` seeds the gang's member map so ``fail_slice`` /
        ``release_idle_gangs`` keep working for scheduler-bound gangs."""
        with self._lock:
            found = self._find_free_slices(accelerator_type, n_slices)
            if found is None:
                return None
            gang = self._gangs.setdefault(
                gang_name,
                _Gang(gang_name, size or (len(pods) if pods else 1),
                      accelerator_type, num_slices=n_slices))
            if pods:
                gang.pods.update(pods)
            self._bind_locked(gang, found)
            return list(gang.slice_names)

    def note_gang_pod(self, gang_name: str, pod: Pod) -> None:
        """Record a member pod on an already-bound gang.  The scheduler
        front-end admits pods without calling :meth:`offer`, and an
        elastic re-shard replaces EVERY pod of an admitted gang without
        rebinding — without this, the node-side idle reaper only sees
        the dead generation's keys and frees the slices out from under
        the running gang."""
        with self._lock:
            g = self._gangs.get(gang_name)
            if g is not None:
                g.pods[f"{pod.metadata.namespace}/{pod.metadata.name}"] = pod

    def release_slices(self, gang_name: str, n_release: int) -> List[str]:
        """Partial release (elastic width harvesting): unbind ``n_release``
        of the gang's bound slices and return their names.  The released
        set is chosen to break the FEWEST adjacency domains: the
        coordinator's slice (bind position 0) is always kept, the
        coordinator's domain is preferred whole, and remaining keeps fill
        from the largest surviving domain groups — so the surviving set
        stays as contiguous as the binding allows.  At least one slice
        survives.  With no topology info (every slice its own domain) this
        reduces to releasing the LAST ``n_release`` slices, the historical
        behavior harvest callers rely on."""
        with self._lock:
            g = self._gangs.get(gang_name)
            if g is None or n_release <= 0:
                return []
            n_release = min(n_release, max(0, len(g.slice_names) - 1))
            if n_release <= 0:
                return []
            names = list(g.slice_names)
            keep_n = len(names) - n_release
            # Group bind positions 1.. by domain (dict order = first
            # occurrence); position 0 (coordinator) is always kept.
            def dom_of(pos: int) -> str:
                sl = self.slices.get(names[pos])
                return dcn_domain(sl) if sl is not None else names[pos]
            coord_dom = dom_of(0)
            groups: Dict[str, List[int]] = {}
            for pos in range(1, len(names)):
                groups.setdefault(dom_of(pos), []).append(pos)
            ordered = sorted(
                groups.items(),
                key=lambda kv: (kv[0] != coord_dom, -len(kv[1])))
            kept = {0}
            for _dom, positions in ordered:
                for pos in positions:
                    if len(kept) == keep_n:
                        break
                    kept.add(pos)
                if len(kept) == keep_n:
                    break
            released = [names[pos] for pos in range(len(names))
                        if pos not in kept]
            g.slice_names = [names[pos] for pos in sorted(kept)]
            g.num_slices = keep_n
            for name in released:
                sl = self.slices.get(name)
                if sl is not None:
                    self._unbind_locked(sl)
            return released

    def grow_gang(self, gang_name: str, accelerator_type: str,
                  n_extra: int) -> Optional[List[str]]:
        """Bind ``n_extra`` more free slices to an admitted gang
        (elastic re-expansion), all-or-nothing; returns the new slice
        names or None when capacity is short."""
        with self._lock:
            g = self._gangs.get(gang_name)
            if g is None or n_extra <= 0:
                return None
            found = self._find_free_slices(
                accelerator_type, n_extra,
                prefer_domains=self._gang_domains_locked(g))
            if found is None:
                return None
            self._bind_locked(g, found)
            g.num_slices = len(g.slice_names)
            return [sl.name for sl in found]

    def _gang_domains_locked(self, g: _Gang) -> List[str]:
        """Distinct DCN domains of the gang's bound slices, in bind order."""
        out: List[str] = []
        for name in g.slice_names:
            sl = self.slices.get(name)
            dom = dcn_domain(sl) if sl is not None else name
            if dom not in out:
                out.append(dom)
        return out

    def placement_of(self, gang_name: str) -> Optional[Dict[str, object]]:
        """Topology view of an admitted gang's binding: slice names, the
        DCN domains they span, and the adjacency score — what the
        scheduler's placement metrics and ``kctpu describe`` surface."""
        with self._lock:
            g = self._gangs.get(gang_name)
            if g is None or not g.slice_names:
                return None
            domains = self._gang_domains_locked(g)
            return {
                "slices": list(g.slice_names),
                "domains": domains,
                "score": round(
                    adjacency_score(len(g.slice_names), len(domains)), 4),
            }

    def has_free_slice(self, accelerator_type: str = "") -> bool:
        return self.free_slice_count(accelerator_type) > 0

    def free_slice_count(self, accelerator_type: str = "") -> int:
        """O(1) via the free-capacity index — this is the query every
        queued pod's admission poll and the scheduler's harvest/preempt
        sizing hit, so it must not scan the slice table."""
        with self._lock:
            if accelerator_type:
                return self._free_counts.get(accelerator_type, 0)
            return sum(self._free_counts.values())

    def gang_on_slice(self, slice_name: str) -> str:
        with self._lock:
            sl = self.slices.get(slice_name)
            return sl.bound_gang if sl else ""

    def busy_seconds(self) -> float:
        """Total slice-busy seconds across all slices ever bound — completed
        bindings plus the in-flight ones.  The contention bench differences
        two readings to compute utilization over a window."""
        now = time.time()
        with self._lock:
            return self._busy_s + sum(
                max(0.0, now - s.bound_at)
                for s in self.slices.values() if s.bound_gang and s.bound_at)

    def utilization_now(self) -> float:
        """Instantaneous bound fraction of healthy slices (the
        kctpu_slice_utilization gauge callback)."""
        with self._lock:
            healthy = [s for s in self.slices.values() if s.healthy]
            if not healthy:
                return 0.0
            return sum(1 for s in healthy if s.bound_gang) / len(healthy)

    def _find_free_slices(self, accelerator_type: str, n: int,
                          prefer_domains: Iterable[str] = (),
                          ) -> Optional[List[TPUSlice]]:
        """n free healthy slices of the type, or None if fewer exist.

        Adjacency-scored: candidates are grouped by DCN domain and taken
        largest-group-first, so the returned set spans the fewest domains
        reachable from the current free pool (greedy largest-first is
        optimal for "cover n items with fewest groups").  ``prefer_domains``
        biases toward domains the gang already occupies — elastic
        re-expansion stays adjacent to the surviving binding.  Ties keep
        slice-table insertion order, so topology-free inventories (every
        slice its own domain) behave exactly like the old first-fit scan.
        """
        free = [s for s in self.slices.values()
                if not s.bound_gang and s.healthy
                and (not accelerator_type
                     or s.accelerator_type == accelerator_type)]
        if len(free) < n:
            return None
        if self._placement == "random":
            self._rng.shuffle(free)
            return free[:n]
        prefer = set(prefer_domains)
        groups: Dict[str, List[TPUSlice]] = {}
        for s in free:
            groups.setdefault(dcn_domain(s), []).append(s)
        ordered = sorted(
            groups.items(),
            key=lambda kv: (kv[0] not in prefer, -len(kv[1])))
        out: List[TPUSlice] = []
        for _dom, members in ordered:
            for s in members:
                out.append(s)
                if len(out) == n:
                    return out
        return None  # unreachable: len(free) >= n

    def gang_slice(self, gang_name: str) -> str:
        with self._lock:
            g = self._gangs.get(gang_name)
            return g.slice_name if g else ""

    def gang_slices(self, gang_name: str) -> List[str]:
        with self._lock:
            g = self._gangs.get(gang_name)
            return list(g.slice_names) if g else []

    def release_gang(self, gang_name: str) -> None:
        """Free every bound slice when a job completes or is recycled."""
        with self._lock:
            g = self._gangs.pop(gang_name, None)
            for name in (g.slice_names if g else []):
                if name in self.slices:
                    self._unbind_locked(self.slices[name])

    def release_idle_gangs(self, active_pod_keys) -> List[str]:
        """Release every gang none of whose member pods is still active —
        the node-side backstop that frees slices when the controller that
        acquired them runs in another process (REST/two-process mode, where
        the controller's ``release_gang`` calls happen against a different
        ``TPUInventory`` instance — or none at all).  Idempotent with the
        controller's own terminal-cleanup release.

        A gang is only released after being idle in TWO consecutive calls:
        a gang admitted between the caller's pod-list snapshot and this call
        would otherwise be released while its (running) pods proceed —
        running pods never re-offer, so slice exclusivity would break.  The
        second call sees a fresh snapshot containing those pods and clears
        the candidacy.

        ``active_pod_keys`` are namespace-qualified "namespace/name" keys
        (the kubelet's own key format): a bare-name match would let a
        same-named pod in another namespace keep a dead gang's slices
        bound forever."""
        active = set(active_pod_keys)
        with self._lock:
            idle = {name for name, g in self._gangs.items()
                    if not (set(g.pods) & active)}
            confirmed = list(idle & self._idle_candidates)
            self._idle_candidates = idle - set(confirmed)
        for name in confirmed:
            self.release_gang(name)
        return confirmed

    def fail_slice(self, slice_name: str) -> List[str]:
        """Simulate a whole-slice failure (the TPU failure domain).  The
        slice is quarantined (healthy=False: it never admits another gang)
        and the bound gang is evicted from ALL its slices (one slice dying
        tears the collective for the whole multislice gang; the other
        slices stay healthy and are freed for the replacement).  Returns
        the "namespace/name" keys of pods in the evicted gang; the kubelet
        fails them all."""
        with self._lock:
            sl = self.slices.get(slice_name)
            if sl is None:
                return []
            if sl.healthy and not sl.bound_gang:
                self._free_counts[sl.accelerator_type] -= 1
            sl.healthy = False
            self._version += 1
            if not sl.bound_gang:
                return []
            g = self._gangs.pop(sl.bound_gang, None)
            for name in (g.slice_names if g else [sl.name]):
                if name in self.slices:
                    self._unbind_locked(self.slices[name])
            return list(g.pods.keys()) if g else []


# The name the capacity-plane docs/ISSUE use; same class.
TPUSliceInventory = TPUInventory
