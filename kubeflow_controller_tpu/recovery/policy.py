"""Restart policy engine: per-replica restart accounting, backoff, limits.

The reference observes pod failures and does nothing (design_doc.md:228-260);
PR 1-8 grew an index-preserving replacement path (planner/plan.py) but it is
policy-free: a Failed pod is replaced *immediately* and *forever* — a crash
loop restarts at full speed until someone deletes the job.  This module is
the k8s-Job-shaped policy in front of that path:

- **accounting**: every distinct failed pod observed at a replica index is
  one restart (pod names are unique via generateName, so observation across
  many syncs counts each failure exactly once);
- **backoff**: the FIRST failure in a streak restarts immediately (a slice
  loss or a one-off crash should recover at full speed — the slice-failure
  and preemption benches depend on it), subsequent failures wait
  ``initial_backoff_s * factor^(streak-2)`` capped at ``max_backoff_s``,
  with multiplicative jitter so a wide job's crash-looping replicas do not
  re-create in lockstep;
- **limit**: a streak longer than ``spec.backoff_limit`` is terminal — the
  planner stops replacing, the updater rolls the job up to ``Failed`` with
  a ``BackoffLimitExceeded`` reason;
- **reset**: ``reset_after_s`` of continuous Running clears the streak
  (the CrashLoopBackOff recovery rule), while the monotonic ``total``
  feeds the status/CLI RESTARTS column;
- **exemption**: pods failed by the capacity plane (``reason=Preempted…``)
  are NOT restarts — preemption is scheduling, not failure, and its
  readmission latency is the warm-pool path's whole point.

The tracker is observation-driven and thread-safe; :meth:`RestartTracker.assess`
is called once per sync and returns a :class:`RecoveryAssessment` the
planner (gate replacements), updater (status restarts / terminal reason)
and controller (events, requeue-after, gang-generation bump) all consume.
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from ..api.core import PHASE_FAILED, PHASE_RUNNING, PHASE_SUCCEEDED, is_pod_active
from ..api.tfjob import ReplicaType, TFJob
from ..obs.phases import (
    POD_REASON_HARVESTED_PREFIX,
    POD_REASON_PREEMPTED_PREFIX,
)
from ..utils import locks
from ..planner.materialize import pods_by_index
from ..planner.plan import desired_replicas

# Decision actions.
ACTION_REPLACE = "replace"      # re-create now (backoff elapsed or first failure)
ACTION_BACKOFF = "backoff"      # failed, but the backoff window is still open
ACTION_EXHAUSTED = "exhausted"  # streak > backoffLimit: terminal Failed
ACTION_NEVER = "never"          # restartPolicy Never: terminal by policy


@dataclass
class RestartPolicyConfig:
    """Controller-level knobs (the per-job limit lives on the spec)."""

    # First failure in a streak restarts immediately; the second waits
    # initial_backoff_s, then * factor per further failure, capped.
    initial_backoff_s: float = 0.25
    backoff_factor: float = 2.0
    max_backoff_s: float = 30.0
    # Multiplicative jitter: the delay is scaled by uniform(1, 1+jitter).
    jitter: float = 0.1
    # Continuous Running that resets the streak (not the monotonic total).
    reset_after_s: float = 600.0


@dataclass
class RestartDecision:
    action: str
    count: int = 0        # monotonic failures at this index (status/CLI)
    streak: int = 0       # resettable consecutive-failure run (backoff input)
    delay_s: float = 0.0  # backoff applied to this restart
    remaining_s: float = 0.0  # backoff left (action == ACTION_BACKOFF)
    reason: str = ""      # coarse pod failure reason


@dataclass
class NewFailure:
    """A failed pod seen for the first time this sync (one event each)."""

    type: ReplicaType
    index: int
    pod_name: str
    reason: str
    decision: RestartDecision


@dataclass
class RecoveryAssessment:
    """One sync's restart-policy verdict for a job."""

    decisions: Dict[Tuple[ReplicaType, int], RestartDecision] = field(
        default_factory=dict)
    new_failures: List[NewFailure] = field(default_factory=list)
    newly_exhausted: List[Tuple[ReplicaType, int, RestartDecision]] = field(
        default_factory=list)
    # Monotonic restart totals per replica type (TFReplicaStatus.restarts).
    counts: Dict[ReplicaType, int] = field(default_factory=dict)
    # Soonest backoff expiry across indices (0 = nothing waiting): the
    # controller requeues the key after this, since a pod already Failed
    # generates no further watch events to re-trigger the sync.
    requeue_after_s: float = 0.0

    def decision_for(self, typ: ReplicaType,
                     index: int) -> Optional[RestartDecision]:
        return self.decisions.get((typ, index))

    def exhausted(self, typ: ReplicaType) -> Set[int]:
        return {i for (t, i), d in self.decisions.items()
                if t == typ and d.action == ACTION_EXHAUSTED}

    def restarts_for(self, typ: ReplicaType) -> int:
        return self.counts.get(typ, 0)


class _IndexState:
    __slots__ = ("failed_pods", "total", "streak", "ready_at", "delay_s",
                 "pending_since", "exhausted_emitted", "running_pod",
                 "running_since")

    def __init__(self):
        self.failed_pods: Set[str] = set()
        self.total = 0
        self.streak = 0
        self.ready_at = 0.0
        self.delay_s = 0.0
        self.pending_since = 0.0   # first failure time awaiting a replacement
        self.exhausted_emitted = False
        self.running_pod = ""
        self.running_since = 0.0


def _coarse_reason(reason: str) -> str:
    """Bounded-cardinality metric label from a free-form pod reason:
    the leading token ("Error", "SliceFailed", "ChaosKill", "GangBroken")."""
    if not reason:
        return "unknown"
    return reason.split(":", 1)[0].split(None, 1)[0][:32]


class RestartTracker:
    """Per-(job, replica type, index) restart accounting + decisions."""

    def __init__(self, config: Optional[RestartPolicyConfig] = None,
                 rng: Optional[random.Random] = None):
        self.config = config or RestartPolicyConfig()
        self._rng = rng or random.Random()
        self._lock = locks.named_lock("recovery.restarts")
        # job key -> (type, index) -> state
        self._jobs: Dict[str, Dict[Tuple[ReplicaType, int], _IndexState]] = {}
        from ..obs.metrics import REGISTRY

        self._c_restarts = REGISTRY.counter(
            "kctpu_replica_restarts_total",
            "Replica restarts planned by the recovery policy, by coarse "
            "pod failure reason", ("reason",))
        self._h_latency = REGISTRY.histogram(
            "kctpu_restart_latency_seconds",
            "Failure observed -> replacement replica Running",
            buckets=(0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10, 30, 60, 120))
        self._h_backoff = REGISTRY.histogram(
            "kctpu_restart_backoff_seconds",
            "Backoff applied before a replica restart",
            buckets=(0.0, 0.1, 0.25, 0.5, 1, 2.5, 5, 10, 30, 60))

    # ---------------------------------------------------------------- assess

    def assess(self, key: str, job: TFJob, pods_by_type, now: float
               ) -> RecoveryAssessment:
        """Observe one sync's pod view; return decisions for every replica
        index that currently has a terminal-failed pod and no live/succeeded
        replacement."""
        out = RecoveryAssessment()
        limit = job.spec.backoff_limit
        with self._lock:
            states = self._jobs.setdefault(key, {})
            for spec in job.spec.tf_replica_specs:
                typ = spec.tf_replica_type
                restart = (spec.template.spec.restart_policy
                           if spec.template else "OnFailure")
                replace = restart in ("OnFailure", "Always")
                by_idx = pods_by_index(pods_by_type.get(typ, []))
                for i in range(desired_replicas(spec)):
                    plist = by_idx.get(i, [])
                    st = states.get((typ, i))
                    running = next((p for p in plist
                                    if p.status.phase == PHASE_RUNNING), None)
                    if running is not None and st is not None:
                        self._observe_running(st, running.metadata.name, now)
                    # Count failures not injected by the scheduler: a
                    # preemption is capacity policy, not a crash, and must
                    # not burn the backoff budget or delay readmission.
                    # Width harvesting (elastic plane) is the same class —
                    # the scheduler took capacity; the member did nothing
                    # wrong, and the re-shard must not inherit a backoff.
                    failed = [p for p in plist
                              if p.status.phase == PHASE_FAILED
                              and not (p.status.reason or "").startswith(
                                  (POD_REASON_PREEMPTED_PREFIX,
                                   POD_REASON_HARVESTED_PREFIX))]
                    fresh = [p for p in failed
                             if st is None
                             or p.metadata.name not in st.failed_pods]
                    if fresh:
                        if st is None:
                            st = states.setdefault((typ, i), _IndexState())
                        self._record_failures(st, fresh, replace, now)
                        for p in fresh:
                            out.new_failures.append(NewFailure(
                                typ, i, p.metadata.name,
                                p.status.reason or "", None))
                    if st is not None:
                        out.counts[typ] = out.counts.get(typ, 0) + st.total
                    # A decision exists only while the failure is unresolved:
                    # failed record(s) present, nothing alive or done at the
                    # index yet.
                    blocked = any(is_pod_active(p) for p in plist) or any(
                        p.status.phase == PHASE_SUCCEEDED for p in plist)
                    if not failed or blocked or st is None:
                        continue
                    d = self._decide(st, replace, limit, now)
                    d.reason = failed[-1].status.reason or ""
                    out.decisions[(typ, i)] = d
                    if d.action == ACTION_BACKOFF:
                        rem = d.remaining_s
                        if (out.requeue_after_s == 0.0
                                or rem < out.requeue_after_s):
                            out.requeue_after_s = rem
                    if (d.action == ACTION_EXHAUSTED
                            and not st.exhausted_emitted):
                        st.exhausted_emitted = True
                        out.newly_exhausted.append((typ, i, d))
        # Attach decisions to the new-failure records (post-decision: the
        # decision reflects ALL failures seen this sync, not a partial view).
        for nf in out.new_failures:
            nf.decision = out.decisions.get((nf.type, nf.index)) or \
                RestartDecision(ACTION_REPLACE, reason=nf.reason)
        return out

    def _observe_running(self, st: _IndexState, pod_name: str,
                         now: float) -> None:
        if st.running_pod != pod_name:
            st.running_pod = pod_name
            st.running_since = now
            if st.pending_since and pod_name not in st.failed_pods:
                # Replacement reached Running: the restart latency sample.
                self._h_latency.observe(max(0.0, now - st.pending_since))
                st.pending_since = 0.0
        elif (st.streak and self.config.reset_after_s > 0
              and now - st.running_since >= self.config.reset_after_s):
            st.streak = 0  # healthy long enough: forgive the streak

    def _record_failures(self, st: _IndexState, fresh, replace: bool,
                         now: float) -> None:
        cfg = self.config
        for p in fresh:
            st.failed_pods.add(p.metadata.name)
            st.total += 1
            st.streak += 1
            if replace:
                self._c_restarts.labels(
                    _coarse_reason(p.status.reason or "")).inc()
        if not st.pending_since:
            st.pending_since = now
        delay = 0.0
        if st.streak > 1:
            delay = min(
                cfg.initial_backoff_s
                * (cfg.backoff_factor ** (st.streak - 2)),
                cfg.max_backoff_s)
            if cfg.jitter > 0:
                delay *= 1.0 + self._rng.uniform(0.0, cfg.jitter)
        st.delay_s = delay
        st.ready_at = now + delay
        if replace:
            self._h_backoff.observe(delay)

    def _decide(self, st: _IndexState, replace: bool, limit: int,
                now: float) -> RestartDecision:
        if not replace:
            return RestartDecision(ACTION_NEVER, count=st.total,
                                   streak=st.streak)
        if limit >= 0 and st.streak > limit:
            return RestartDecision(ACTION_EXHAUSTED, count=st.total,
                                   streak=st.streak, delay_s=st.delay_s)
        if now < st.ready_at:
            return RestartDecision(ACTION_BACKOFF, count=st.total,
                                   streak=st.streak, delay_s=st.delay_s,
                                   remaining_s=st.ready_at - now)
        return RestartDecision(ACTION_REPLACE, count=st.total,
                               streak=st.streak, delay_s=st.delay_s)

    # -------------------------------------------------------------- plumbing

    def backoff_schedule(self, streaks) -> List[float]:
        """The deterministic (jitter-free) delay for each streak length in
        ``streaks`` — the schedule tests pin down."""
        cfg = self.config
        out = []
        for s in streaks:
            if s <= 1:
                out.append(0.0)
            else:
                out.append(min(
                    cfg.initial_backoff_s * (cfg.backoff_factor ** (s - 2)),
                    cfg.max_backoff_s))
        return out

    def restarts(self, key: str) -> Dict[ReplicaType, int]:
        """Monotonic restart totals per type (for status without a sync)."""
        out: Dict[ReplicaType, int] = {}
        with self._lock:
            for (typ, _), st in self._jobs.get(key, {}).items():
                out[typ] = out.get(typ, 0) + st.total
        return out

    def forget_job(self, key: str) -> None:
        with self._lock:
            self._jobs.pop(key, None)

    def __len__(self) -> int:
        with self._lock:
            return sum(len(v) for v in self._jobs.values())
