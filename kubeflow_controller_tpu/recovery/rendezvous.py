"""Gang re-rendezvous: broken-cluster detection and cooperative tear-down.

A ``jax.distributed`` gang that loses a member does not fail cleanly — the
survivors block inside the next collective, forever, while the controller
sees N-1 perfectly Running pods (the reference's worst failure mode, and
exactly what Podracer's decoupled-host design avoids — PAPERS.md).  Torn
collectives cannot be rejoined process-by-process, so the recovery shape is:

1. every member checkpoints continuously (``spec.checkpoint_every_steps``,
   trainer.train_step_loop_dist) — the "checkpoint" half is *already done*
   by the time anything breaks;
2. each member runs a :class:`GangGuard`: a heartbeat file per member in
   the node-shared rendezvous dir (the PR-8 readiness-drop dir) plus a
   monitor thread that watches the peers' files — a peer whose heartbeat
   goes stale past the deadline WITHOUT a clean ``.done`` marker means the
   gang is torn;
3. on detection the survivor tears itself down (``os._exit(EXIT_REJOIN)``
   by default): its pod fails with ``GangBroken`` instead of hanging
   Running, the controller's restart policy replaces the WHOLE gang
   index-preserved (planner gang semantics), and the replacement gang —
   stamped with a controller-bumped **gang generation** annotation/env —
   re-enters rendezvous coordinator-first (generation-keyed PR-8 readiness
   drops, so stale ready files from the dead generation cannot fake
   coordinator liveness) and restores from the latest checkpoint.

Recovery is therefore restore + compile-cache-hit (PR 8), not
hang-forever and not restart-from-step-0.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Callable, Optional

logger = logging.getLogger("kubeflow_controller_tpu.recovery")

# Exit code a gang member uses for cooperative tear-down on peer loss: the
# kubelet maps it to a Failed pod with reason "GangBroken" (never an
# in-place restart — the gang is replaced as a unit).
EXIT_REJOIN = 64

# Opt-in env for the workload-side guard (set by the chaos bench and by
# deployments that want survivor self-detection; the fake kubelet notices
# a SIGKILLed process immediately, so in-process runs work without it).
ENV_GANG_MONITOR = "KCTPU_GANG_MONITOR"
# Controller-bumped gang generation (annotation + env, stamped by the
# planner; see planner/materialize.py ENV_GANG_GENERATION).
ENV_GANG_GENERATION = "KCTPU_GANG_GENERATION"


def generation_from_env(env=None) -> int:
    e = os.environ if env is None else env
    try:
        return int(e.get(ENV_GANG_GENERATION, "0") or "0")
    except ValueError:
        return 0


class GangGuard:
    """Per-member gang liveness: writes this member's heartbeat file and
    watches the peers'.

    File layout under ``directory`` (generation-scoped so a replacement
    gang never reads the dead generation's files):

    - ``<gang>-g<gen>-m<i>.alive`` — touched every ``interval_s``; mtime is
      the liveness signal;
    - ``<gang>-g<gen>-m<i>.done``  — dropped by a member that finished
      CLEANLY, written *before* the end-of-job barrier so a fast peer's
      exit is never mistaken for death.

    A peer is declared dead when its heartbeat has been seen at least once
    and then goes stale past ``timeout_s`` (never-seen peers get
    ``startup_grace_s`` — they may still be in image pull / rendezvous).
    ``on_broken(member_index)`` runs once, from the monitor thread; the
    default handler logs and ``os._exit(EXIT_REJOIN)`` — see module doc for
    why exiting (not rejoining in-process) is the correct tear-down.
    """

    def __init__(self, directory: str, gang: str, member: int, peers: int,
                 generation: int = 0, interval_s: float = 0.5,
                 timeout_s: float = 5.0, startup_grace_s: float = 120.0,
                 on_broken: Optional[Callable[[int], None]] = None):
        self.directory = directory
        self.gang = gang
        self.member = member
        self.peers = peers
        self.generation = generation
        self.interval_s = interval_s
        self.timeout_s = timeout_s
        self.startup_grace_s = startup_grace_s
        self._on_broken = on_broken or self._default_on_broken
        self._seen: dict = {}  # member index -> last observed mtime
        self._stop = threading.Event()
        self._fired = False
        self._thread: Optional[threading.Thread] = None
        self._t0 = 0.0

    # -- file naming ---------------------------------------------------------

    def _base(self, member: int) -> str:
        safe = self.gang.replace("/", "_").replace(":", "_")
        return os.path.join(self.directory,
                            f"{safe}-g{self.generation}-m{member}")

    def alive_file(self, member: int) -> str:
        return self._base(member) + ".alive"

    def done_file(self, member: int) -> str:
        return self._base(member) + ".done"

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "GangGuard":
        if self._thread is not None:
            return self
        self._t0 = time.monotonic()
        self._touch()
        self._thread = threading.Thread(target=self._loop,
                                        name="gang-guard", daemon=True)
        self._thread.start()
        return self

    def mark_done(self) -> None:
        """Clean completion: write the done marker (peers will not treat the
        heartbeat going silent as death) and stop monitoring."""
        try:
            with open(self.done_file(self.member), "w") as fh:
                fh.write(str(os.getpid()))
        except OSError:
            pass
        self.stop()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=self.interval_s * 4 + 1.0)
        self._thread = None

    # -- internals -----------------------------------------------------------

    def _touch(self) -> None:
        path = self.alive_file(self.member)
        try:
            with open(path, "a"):
                pass
            os.utime(path, None)
        except OSError:
            pass  # liveness publishing is best-effort, like heartbeats

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            self._touch()
            dead = self.check_peers()
            if dead is not None and not self._fired:
                self._fired = True
                try:
                    self._on_broken(dead)
                finally:
                    return

    def check_peers(self) -> Optional[int]:
        """One observation pass; returns a dead peer's index or None."""
        now = time.time()
        for j in range(self.peers):
            if j == self.member:
                continue
            if os.path.exists(self.done_file(j)):
                continue  # finished cleanly: silence is not death
            try:
                mtime = os.path.getmtime(self.alive_file(j))
            except OSError:
                # Never seen: startup grace (rendezvous barriers mean the
                # fit cannot have started without this peer anyway).
                if (self._seen.get(j) is None
                        and time.monotonic() - self._t0
                        < self.startup_grace_s):
                    continue
                if self._seen.get(j) is None:
                    return j  # grace expired and never appeared
                return j      # file vanished after being seen
            self._seen[j] = mtime
            if now - mtime > self.timeout_s:
                return j
        return None

    def _default_on_broken(self, member: int) -> None:
        logger.warning(
            "gang %s generation %d: member %d heartbeat lost — tearing down "
            "for re-rendezvous (exit %d); latest checkpoint will be restored "
            "by the replacement gang", self.gang, self.generation, member,
            EXIT_REJOIN)
        # Flush whatever the process can flush; the pod fails with
        # GangBroken and the controller replaces the whole gang.
        try:
            from ..obs import trace as obs_trace

            obs_trace.dump_to_env_dir()
        except Exception:  # noqa: BLE001
            pass
        os._exit(EXIT_REJOIN)


def guard_from_env(rt, env=None) -> Optional[GangGuard]:
    """Build (but do not start) the workload-side guard from the node-agent
    env contract: enabled when ``KCTPU_GANG_MONITOR`` is set, the job is
    multi-process, and a shared rendezvous dir exists.  ``rt`` is the
    :class:`workloads.runtime.JobRuntime`."""
    e = os.environ if env is None else env
    if not e.get(ENV_GANG_MONITOR):
        return None
    d = e.get("KCTPU_RENDEZVOUS_DIR", "")
    if not d or rt.num_processes <= 1:
        return None
    gang = e.get("KCTPU_GANG_NAME", "") or rt.coordinator or "gang"
    try:
        timeout_s = float(e.get("KCTPU_GANG_MONITOR_TIMEOUT", "5.0"))
    except ValueError:
        timeout_s = 5.0
    return GangGuard(d, gang, rt.process_id, rt.num_processes,
                     generation=rt.gang_generation, timeout_s=timeout_s)
