"""Recovery plane: restart policy, gang re-rendezvous, chaos injection.

The reference's own design doc names "no recovery from pod failure" as its
flagship gap (design_doc.md:228-260, PAPER.md §0).  This package closes it
across all three layers:

- :mod:`.policy` — the controller-side restart policy engine: per-replica
  restart accounting with exponential backoff + jitter and a
  ``backoffLimit`` that turns a crash loop into terminal ``Failed``
  (driven off ``spec.template.spec.restart_policy``, like k8s Jobs);
- :mod:`.rendezvous` — the workload-side gang guard: peer-liveness
  heartbeat files and the cooperative tear-down (exit ``EXIT_REJOIN``)
  that turns "survivor hangs in a torn collective forever" into
  "survivor checkpoints continuously and re-enters rendezvous in the
  next gang generation";
- :mod:`.chaos` — the fault injector behind ``bench.py --chaos`` and
  ``make chaos-smoke``: SIGKILL executed pods (or flip simulated pods to
  Failed) at randomized mid-fit times and measure lost steps and
  recovery latency.
"""

from .policy import (  # noqa: F401
    ACTION_BACKOFF,
    ACTION_EXHAUSTED,
    ACTION_NEVER,
    ACTION_REPLACE,
    RecoveryAssessment,
    RestartDecision,
    RestartPolicyConfig,
    RestartTracker,
)
from .rendezvous import (  # noqa: F401
    ENV_GANG_MONITOR,
    EXIT_REJOIN,
    GangGuard,
)
