"""Chaos harness: kill pods mid-fit, measure what recovery actually costs.

This is the fault injector behind ``bench.py --chaos`` and the standing
``make chaos-smoke`` robustness gate.  It does two things:

- **inject**: SIGKILL an executed pod's process (real subprocess, real
  half-written state), or flip a simulated pod to ``Failed`` through the
  same injected-failure path slice failures use — at randomized mid-fit
  times, seeded for reproducibility;
- **measure**: for every kill, the step the job had reached when the
  process died (from the progress plane), the step the replacement resumed
  from (``resumed_from_step``, reported by the restored workload), the
  steps lost between the two (bounded by ``spec.checkpoint_every_steps``
  when checkpoint-resume works), and the recovery latency — kill until the
  job's minimum step climbs back past the pre-kill step.

The monkey only *observes* public surfaces (job progress, pod phases), so
the same harness measures any future recovery mechanism unchanged.
"""

from __future__ import annotations

import random
import time
from dataclasses import dataclass, field
from typing import List, Optional


@dataclass
class KillRecord:
    job: str
    pod: str
    mode: str = ""               # "process" | "warm" | "simulated"
    t_kill: float = 0.0
    step_at_kill: int = 0
    recovered: bool = False
    recovery_s: float = 0.0      # kill -> min step back past step_at_kill
    resumed_from_step: int = -1  # -1 = replacement never reported one
    lost_steps: int = -1         # step_at_kill - resumed_from_step


@dataclass
class ChaosReport:
    kills: List[KillRecord] = field(default_factory=list)

    @property
    def recovered_rate(self) -> float:
        if not self.kills:
            return 0.0
        return sum(1 for k in self.kills if k.recovered) / len(self.kills)

    def recovery_percentile(self, q: float) -> float:
        vals = sorted(k.recovery_s for k in self.kills if k.recovered)
        if not vals:
            return 0.0
        return vals[min(len(vals) - 1,
                        int(round(q / 100.0 * (len(vals) - 1))))]

    @property
    def max_lost_steps(self) -> int:
        known = [k.lost_steps for k in self.kills if k.lost_steps >= 0]
        return max(known) if known else -1


class ChaosMonkey:
    """Seeded fault injector over one fake cluster + kubelet."""

    def __init__(self, cluster, kubelet, seed: int = 0):
        self.cluster = cluster
        self.kubelet = kubelet
        self.rng = random.Random(seed)
        from ..obs.metrics import REGISTRY

        self._c_kills = REGISTRY.counter(
            "kctpu_chaos_kills_total", "Chaos faults injected", ("mode",))

    # -- injection -----------------------------------------------------------

    def kill_pod(self, namespace: str, name: str) -> Optional[KillRecord]:
        """Kill one pod the way its mode dies for real: SIGKILL the
        subprocess (cold or warm-forked), else flip the simulated pod to
        Failed through the kubelet's injected-failure path."""
        mode = self.kubelet.chaos_kill(namespace, name)
        if mode is None:
            return None
        self._c_kills.labels(mode).inc()
        rec = KillRecord(job="", pod=name, mode=mode, t_kill=time.time())
        return rec

    def pick_victim(self, pods) -> Optional[object]:
        """A uniformly random active pod (seeded rng)."""
        cands = [p for p in pods if p.status.phase == "Running"]
        if not cands:
            return None
        return self.rng.choice(cands)

    def kill_at_step(self, namespace: str, job_name: str, min_step: int,
                     deadline_s: float = 120.0,
                     poll_s: float = 0.01) -> Optional[KillRecord]:
        """Wait until ``job_name``'s progress reaches ``min_step`` mid-fit,
        then SIGKILL one random worker of the job.  Returns the record (with
        ``step_at_kill`` from the progress plane) or None when the job ended
        before the trigger."""
        from ..api.tfjob import TFJobPhase

        end = time.time() + deadline_s
        while time.time() < end:
            j = self.cluster.tfjobs.get(namespace, job_name)
            if j.status.phase in (TFJobPhase.SUCCEEDED, TFJobPhase.FAILED):
                return None  # finished before we could strike
            p = j.status.progress
            if p is not None and p.step >= min_step:
                pods = [q for q in self.cluster.pods.list(namespace)
                        if q.metadata.labels.get("tf_job_name") == job_name]
                victim = self.pick_victim(pods)
                if victim is None:
                    return None
                rec = self.kill_pod(namespace, victim.metadata.name)
                if rec is None:
                    return None
                rec.job = job_name
                rec.step_at_kill = p.step
                return rec
            time.sleep(poll_s)
        return None

    # -- measurement ---------------------------------------------------------

    def await_recovery(self, namespace: str, rec: KillRecord,
                       deadline_s: float = 180.0,
                       poll_s: float = 0.02) -> KillRecord:
        """Fill in the recovery half of a kill record: first wait for the
        RESET (the job's progress drops below the pre-kill step — the
        replacement gang's restore/restart showing on the step plane;
        surviving replicas' still-high steps must not fake a recovery),
        then recovered = min step climbs back past ``step_at_kill``.  A
        job that reaches Succeeded counts as recovered either way.
        ``resumed_from_step`` is read from the replacement's progress."""
        from ..api.tfjob import TFJobPhase

        end = time.time() + deadline_s
        seen_reset = False
        while time.time() < end:
            j = self.cluster.tfjobs.get(namespace, rec.job)
            p = j.status.progress
            if p is None or p.reporting == 0 or p.step < rec.step_at_kill:
                seen_reset = True
            if p is not None:
                for r in p.replicas:
                    if r.resumed_from_step > 0:
                        rec.resumed_from_step = max(rec.resumed_from_step,
                                                    r.resumed_from_step)
                if seen_reset and p.reporting > 0 and p.step >= rec.step_at_kill:
                    rec.recovered = True
            if j.status.phase == TFJobPhase.SUCCEEDED:
                rec.recovered = True
            elif j.status.phase == TFJobPhase.FAILED:
                break
            if rec.recovered:
                rec.recovery_s = time.time() - rec.t_kill
                if rec.resumed_from_step >= 0:
                    rec.lost_steps = max(
                        0, rec.step_at_kill - rec.resumed_from_step)
                return rec
            time.sleep(poll_s)
        rec.recovery_s = time.time() - rec.t_kill
        return rec
