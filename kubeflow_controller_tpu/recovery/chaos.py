"""Chaos harness: kill pods mid-fit, measure what recovery actually costs.

This is the fault injector behind ``bench.py --chaos`` and the standing
``make chaos-smoke`` robustness gate.  It does two things:

- **inject**: SIGKILL an executed pod's process (real subprocess, real
  half-written state), or flip a simulated pod to ``Failed`` through the
  same injected-failure path slice failures use — at randomized mid-fit
  times, seeded for reproducibility;
- **measure**: for every kill, the step the job had reached when the
  process died (from the progress plane), the step the replacement resumed
  from (``resumed_from_step``, reported by the restored workload), the
  steps lost between the two (bounded by ``spec.checkpoint_every_steps``
  when checkpoint-resume works), and the recovery latency — kill until the
  job's minimum step climbs back past the pre-kill step.

The monkey only *observes* public surfaces (job progress, pod phases), so
the same harness measures any future recovery mechanism unchanged.
"""

from __future__ import annotations

import random
import time
from dataclasses import dataclass, field
from typing import List, Optional


@dataclass
class KillRecord:
    job: str
    pod: str
    mode: str = ""               # "process" | "warm" | "simulated"
    t_kill: float = 0.0
    step_at_kill: int = 0
    recovered: bool = False
    recovery_s: float = 0.0      # kill -> min step back past step_at_kill
    resumed_from_step: int = -1  # -1 = replacement never reported one
    lost_steps: int = -1         # step_at_kill - resumed_from_step


@dataclass
class ElasticRecord:
    """One elastic kill's measured timeline (bench.py --elastic):

    - ``time_to_degraded_s`` — kill → the job training AGAIN at the
      reduced width (width status below spec AND the min step advancing
      past its post-reset restore point);
    - ``degraded_steps_per_sec`` — observed step rate while degraded
      (the "steps/sec > 0 throughout the degraded window" gate: the
      survivors keep training while the replacement warms);
    - ``time_to_restored_s`` — kill → back at full width and advancing;
    - ``degraded_width`` / ``spec_width`` and the resume evidence
      (``resumed_from_step`` per transition: never restore-from-scratch).
    """

    job: str = ""
    spec_width: int = 0
    degraded_width: int = 0
    time_to_degraded_s: float = 0.0
    time_to_restored_s: float = 0.0
    degraded_steps_per_sec: float = 0.0
    degraded_step_samples: int = 0
    degraded_resumed_from: int = -1   # re-shard down restore point
    restored_resumed_from: int = -1   # re-expand restore point
    degraded: bool = False
    restored: bool = False


@dataclass
class ChaosReport:
    kills: List[KillRecord] = field(default_factory=list)

    @property
    def recovered_rate(self) -> float:
        if not self.kills:
            return 0.0
        return sum(1 for k in self.kills if k.recovered) / len(self.kills)

    def recovery_percentile(self, q: float) -> float:
        vals = sorted(k.recovery_s for k in self.kills if k.recovered)
        if not vals:
            return 0.0
        return vals[min(len(vals) - 1,
                        int(round(q / 100.0 * (len(vals) - 1))))]

    @property
    def max_lost_steps(self) -> int:
        known = [k.lost_steps for k in self.kills if k.lost_steps >= 0]
        return max(known) if known else -1


class ChaosMonkey:
    """Seeded fault injector over one fake cluster + kubelet."""

    def __init__(self, cluster, kubelet, seed: int = 0):
        self.cluster = cluster
        self.kubelet = kubelet
        self.rng = random.Random(seed)
        from ..obs.metrics import REGISTRY

        self._c_kills = REGISTRY.counter(
            "kctpu_chaos_kills_total", "Chaos faults injected", ("mode",))

    # -- injection -----------------------------------------------------------

    def kill_pod(self, namespace: str, name: str) -> Optional[KillRecord]:
        """Kill one pod the way its mode dies for real: SIGKILL the
        subprocess (cold or warm-forked), else flip the simulated pod to
        Failed through the kubelet's injected-failure path."""
        mode = self.kubelet.chaos_kill(namespace, name)
        if mode is None:
            return None
        self._c_kills.labels(mode).inc()
        rec = KillRecord(job="", pod=name, mode=mode, t_kill=time.time())
        return rec

    def pick_victim(self, pods) -> Optional[object]:
        """A uniformly random active pod (seeded rng)."""
        cands = [p for p in pods if p.status.phase == "Running"]
        if not cands:
            return None
        return self.rng.choice(cands)

    def kill_at_step(self, namespace: str, job_name: str, min_step: int,
                     deadline_s: float = 120.0,
                     poll_s: float = 0.01) -> Optional[KillRecord]:
        """Wait until ``job_name``'s progress reaches ``min_step`` mid-fit,
        then SIGKILL one random worker of the job.  Returns the record (with
        ``step_at_kill`` from the progress plane) or None when the job ended
        before the trigger."""
        from ..api.tfjob import TFJobPhase

        end = time.time() + deadline_s
        while time.time() < end:
            j = self.cluster.tfjobs.get(namespace, job_name)
            if j.status.phase in (TFJobPhase.SUCCEEDED, TFJobPhase.FAILED):
                return None  # finished before we could strike
            p = j.status.progress
            if p is not None and p.step >= min_step:
                pods = [q for q in self.cluster.pods.list(namespace)
                        if q.metadata.labels.get("tf_job_name") == job_name]
                victim = self.pick_victim(pods)
                if victim is None:
                    return None
                rec = self.kill_pod(namespace, victim.metadata.name)
                if rec is None:
                    return None
                rec.job = job_name
                rec.step_at_kill = p.step
                return rec
            time.sleep(poll_s)
        return None

    # -- measurement ---------------------------------------------------------

    def await_recovery(self, namespace: str, rec: KillRecord,
                       deadline_s: float = 180.0,
                       poll_s: float = 0.02) -> KillRecord:
        """Fill in the recovery half of a kill record: first wait for the
        RESET (the job's progress drops below the pre-kill step — the
        replacement gang's restore/restart showing on the step plane;
        surviving replicas' still-high steps must not fake a recovery),
        then recovered = min step climbs back past ``step_at_kill``.  A
        job that reaches Succeeded counts as recovered either way.
        ``resumed_from_step`` is read from the replacement's progress."""
        from ..api.tfjob import TFJobPhase

        end = time.time() + deadline_s
        seen_reset = False
        while time.time() < end:
            j = self.cluster.tfjobs.get(namespace, rec.job)
            p = j.status.progress
            if p is None or p.reporting == 0 or p.step < rec.step_at_kill:
                seen_reset = True
            if p is not None:
                for r in p.replicas:
                    if r.resumed_from_step > 0:
                        rec.resumed_from_step = max(rec.resumed_from_step,
                                                    r.resumed_from_step)
                if seen_reset and p.reporting > 0 and p.step >= rec.step_at_kill:
                    rec.recovered = True
            if j.status.phase == TFJobPhase.SUCCEEDED:
                rec.recovered = True
            elif j.status.phase == TFJobPhase.FAILED:
                break
            if rec.recovered:
                rec.recovery_s = time.time() - rec.t_kill
                if rec.resumed_from_step >= 0:
                    rec.lost_steps = max(
                        0, rec.step_at_kill - rec.resumed_from_step)
                return rec
            time.sleep(poll_s)
        rec.recovery_s = time.time() - rec.t_kill
        return rec

    def await_elastic(self, namespace: str, rec: KillRecord,
                      spec_width: int, deadline_s: float = 180.0,
                      poll_s: float = 0.02) -> "ElasticRecord":
        """Measure an elastic kill's timeline off the public status
        surface (width rollup + progress plane): time-to-degraded (kill →
        training again at reduced width), the step rate THROUGH the
        degraded window, and time-to-restored (kill → full width and
        advancing).  Observation-only, like :meth:`await_recovery`."""
        from ..api.tfjob import TFJobPhase

        out = ElasticRecord(job=rec.job, spec_width=spec_width)
        end = time.time() + deadline_s
        samples = []  # (t, min step) while degraded, strictly advancing
        last_step = None
        phase = "await-degrade"
        while time.time() < end:
            j = self.cluster.tfjobs.get(namespace, rec.job)
            w = j.status.width
            p = j.status.progress
            now = time.time()
            cur = w.current if w is not None else spec_width
            if p is not None:
                for r in p.replicas:
                    if r.resumed_from_step > 0:
                        if phase in ("await-degrade", "degraded"):
                            out.degraded_resumed_from = max(
                                out.degraded_resumed_from,
                                r.resumed_from_step)
                        else:
                            out.restored_resumed_from = max(
                                out.restored_resumed_from,
                                r.resumed_from_step)
            if phase == "await-degrade":
                # Degraded = the width dropped AND the survivors' min
                # step ADVANCED at that width (a frozen restore doesn't
                # count — the gate is "keeps training").
                if cur < spec_width and p is not None and p.reporting > 0:
                    step = p.step
                    if last_step is not None and step > last_step > 0:
                        out.degraded = True
                        out.degraded_width = cur
                        out.time_to_degraded_s = now - rec.t_kill
                        samples.append((now, step))
                        phase = "degraded"
                    last_step = step
            elif phase == "degraded":
                if cur >= spec_width:
                    phase = "await-restore"
                    last_step = None
                elif p is not None and p.reporting > 0:
                    if samples and p.step > samples[-1][1]:
                        samples.append((now, p.step))
            else:  # await-restore: full width again, advancing again
                if p is not None and p.reporting > 0:
                    step = p.step
                    if last_step is not None and step > last_step:
                        out.restored = True
                        out.time_to_restored_s = now - rec.t_kill
                        break
                    last_step = step
            if j.status.phase == TFJobPhase.SUCCEEDED:
                # Finishing at full width IS restored (the final steps
                # ran post-expand); finishing degraded is not.
                if phase == "await-restore":
                    out.restored = True
                    out.time_to_restored_s = now - rec.t_kill
                break
            if j.status.phase == TFJobPhase.FAILED:
                break
            time.sleep(poll_s)
        if len(samples) >= 2:
            dt = samples[-1][0] - samples[0][0]
            ds = samples[-1][1] - samples[0][1]
            out.degraded_steps_per_sec = (round(ds / dt, 3) if dt > 0
                                          else 0.0)
        out.degraded_step_samples = len(samples)
        return out
