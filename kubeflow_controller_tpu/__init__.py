"""kubeflow_controller_tpu — a TPU-native job orchestration framework.

A brand-new implementation of the capabilities of ``caicloud/kubeflow-controller``
(the 2018 ``TFJob`` Kubernetes controller, reference at /root/reference): a
declarative job resource with PS / Worker / Local — and, new here, **TPU slice** —
replica types, a level-triggered reconcile engine, per-replica cluster-spec
generation, and status rollup.  The workload layer is JAX/XLA-native
(``models/``, ``ops/``, ``parallel/``, ``workloads/``).

Layer map (mirrors SURVEY.md §1):

- ``api/``        — the TFJob resource schema (ref: vendor/.../apis/kubeflow/v1alpha1/types.go)
- ``cluster/``    — in-memory API server + fake kubelet + TPU inventory (test substrate)
- ``controller/`` — reconcile engine: workqueue, informers, expectations, sync loop
                    (ref: pkg/controller/controller.go)
- ``planner/``    — the desired-state diff engine (ref: pkg/tensorflow/)
- ``updater/``    — status rollup (ref: pkg/controller/updater/)
- ``checker/``    — job classification + health (ref: pkg/checker/)
- ``models/``     — JAX/Flax model zoo (MNIST, ResNet-CIFAR, Llama-style transformer)
- ``ops/``        — Pallas TPU kernels with XLA fallbacks
- ``parallel/``   — mesh / sharding / collectives library (dp, fsdp, tp, sp, ring attention)
- ``workloads/``  — runnable training entrypoints the controller launches in pods
- ``cli/``        — process shell (ref: cmd/controller/main.go)
"""

__version__ = "0.1.0"
GIT_SHA = "dev"
