"""Per-job lifecycle histograms: how long jobs spend in each phase.

The status updater (updater/status.py) reports every phase transition it
computes here; the tracker remembers when each job entered its current
phase and, on transition, observes the dwell time into
``kctpu_job_phase_transition_seconds{from_phase,to_phase}``.  The
"Pending" clock starts at the job's ``creationTimestamp`` when known, so
Pending→Running measures the real schedule+start latency, not just the
interval between two syncs.

Keyed on job UID and deduplicated against the *stored* phase: the
controller recomputes status every sync (often with a stale informer
view), so the same transition may be computed repeatedly before the write
lands — only the first observation counts.  Terminal jobs drop their
entry; the table is additionally capacity-bounded so a controller that
churns jobs forever cannot grow it without bound.
"""

from __future__ import annotations

import collections
import time
from typing import Deque, Dict, List, Optional, Tuple

from .metrics import REGISTRY, Registry
from ..utils import locks

TERMINAL_PHASES = ("Succeeded", "Failed")

# Transitions kept per job for the flight recorder's status history.
HISTORY_DEPTH = 32

# Job lifetimes span ms (simulated pods) to hours (real training):
# wider-than-default top end.
_PHASE_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
                  10.0, 30.0, 60.0, 120.0, 300.0, 600.0, 1800.0, 3600.0)


class JobLifecycle:
    def __init__(self, registry: Optional[Registry] = None,
                 max_jobs: int = 4096):
        reg = registry or REGISTRY
        self._hist = reg.histogram(
            "kctpu_job_phase_transition_seconds",
            "Seconds a TFJob spent in from_phase before entering to_phase",
            labelnames=("from_phase", "to_phase"), buckets=_PHASE_BUCKETS)
        self._transitions = reg.counter(
            "kctpu_job_phase_transitions_total",
            "TFJob phase transitions observed by the status updater",
            labelnames=("from_phase", "to_phase"))
        self._lock = locks.named_lock("obs.lifecycle")
        self._max = max_jobs
        # uid -> (current phase, entered-at wall clock)
        self._since: Dict[str, Tuple[str, float]] = {}
        # uid -> ring of {from, to, at, dwell_s}: the status history the
        # flight recorder (obs/flight.py) folds into postmortem bundles.
        # Kept past terminal transitions (the bundle is written AFTER the
        # job fails), bounded by the same eviction budget as _since.
        self._history: Dict[str, Deque[Dict[str, object]]] = {}

    def observe(self, uid: str, prev_phase: str, new_phase: str,
                now: Optional[float] = None,
                created: Optional[float] = None) -> None:
        """Report that ``uid`` was computed to move prev_phase→new_phase."""
        if not uid or new_phase == prev_phase:
            return
        t = now if now is not None else time.time()
        with self._lock:
            phase, since = self._since.get(uid, (None, None))
            if phase is None:
                # First sighting: treat creation as the start of the initial
                # phase ("None"/"Pending" both mean "not yet running").
                phase = prev_phase
                since = created if created is not None else t
            if phase == new_phase:
                return  # recomputed transition (stale informer view)
            dwell = max(0.0, t - since)
            if new_phase in TERMINAL_PHASES:
                self._since.pop(uid, None)
            else:
                if uid not in self._since and len(self._since) >= self._max:
                    # Bounded: evict the oldest entry (insertion order).
                    self._since.pop(next(iter(self._since)))
                self._since[uid] = (new_phase, t)
            ring = self._history.get(uid)
            if ring is None:
                if len(self._history) >= self._max:
                    self._history.pop(next(iter(self._history)))
                ring = self._history[uid] = collections.deque(
                    maxlen=HISTORY_DEPTH)
            ring.append({"from": phase, "to": new_phase, "at": t,
                         "dwell_s": round(dwell, 3)})
        self._hist.labels(from_phase=phase, to_phase=new_phase).observe(dwell)
        self._transitions.labels(from_phase=phase, to_phase=new_phase).inc()

    def history(self, uid: str) -> List[Dict[str, object]]:
        """Recent phase transitions of ``uid``, oldest first."""
        with self._lock:
            ring = self._history.get(uid)
            return [dict(h) for h in ring] if ring else []

    def forget(self, uid: str) -> None:
        """Drop all state for ``uid`` (job object deleted)."""
        with self._lock:
            self._since.pop(uid, None)
            self._history.pop(uid, None)

    def tracked(self) -> int:
        with self._lock:
            return len(self._since)


_DEFAULT: Optional[JobLifecycle] = None
_DEFAULT_LOCK = locks.named_lock("obs.lifecycle-default")


def job_lifecycle() -> JobLifecycle:
    """The process-global tracker (bound to the global registry)."""
    global _DEFAULT
    with _DEFAULT_LOCK:
        if _DEFAULT is None:
            _DEFAULT = JobLifecycle()
        return _DEFAULT
