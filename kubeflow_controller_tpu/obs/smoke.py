"""`make metrics-smoke`: boot the in-process cluster, scrape /metrics, fail
on malformed exposition.

Runs the whole loop for real — HTTP API server, simulated kubelet,
controller, one 2-worker TFJob to Succeeded — then fetches ``GET /metrics``
over the wire, validates every line (:func:`..obs.metrics.validate_exposition`),
and asserts the headline families are present.  Exit 0 = healthy surface.
"""

from __future__ import annotations

import sys
import time
import urllib.request


REQUIRED_FAMILIES = (
    "kctpu_reconcile_duration_seconds",
    "kctpu_controller_syncs_total",
    "kctpu_workqueue_depth",
    "kctpu_workqueue_queue_duration_seconds",
    "kctpu_job_phase_transition_seconds",
    "kctpu_gather_indexed_total",
    "kctpu_gather_full_lists_total",
    # Progress plane (simulated heartbeats feed these during the run).
    "kctpu_job_step",
    "kctpu_job_examples_per_sec",
    "kctpu_job_stalled",
    "kctpu_job_straggler_lag_steps",
)


def main() -> int:
    from ..api.core import Container, PodTemplateSpec
    from ..api.meta import ObjectMeta
    from ..api.tfjob import ReplicaType, TFJob, TFJobPhase, TFReplicaSpec
    from ..cluster import Cluster, FakeKubelet, PhasePolicy
    from ..cluster.apiserver import FakeAPIServer
    from ..controller import Controller
    from .metrics import validate_exposition

    cluster = Cluster()
    server = FakeAPIServer(cluster.store)
    url = server.start()
    # heartbeat_s > 0: simulated workers publish PodProgress beats, so the
    # scrape must show the progress-plane gauges populated by the sync.
    kubelet = FakeKubelet(cluster, policy=PhasePolicy(run_s=0.2,
                                                      heartbeat_s=0.02))
    ctrl = Controller(cluster, resync_period_s=1.0)
    kubelet.start()
    ctrl.run(threadiness=2)
    try:
        job = TFJob(metadata=ObjectMeta(name="smoke", namespace="default"))
        for typ, n in ((ReplicaType.PS, 1), (ReplicaType.WORKER, 2)):
            t = PodTemplateSpec()
            t.spec.containers.append(Container(name="tensorflow", image="img"))
            t.spec.restart_policy = "OnFailure"
            job.spec.tf_replica_specs.append(
                TFReplicaSpec(replicas=n, tf_replica_type=typ, template=t))
        cluster.tfjobs.create(job)
        deadline = time.time() + 30
        while time.time() < deadline:
            if (cluster.tfjobs.get("default", "smoke").status.phase
                    == TFJobPhase.SUCCEEDED):
                break
            time.sleep(0.05)
        else:
            print("smoke job never reached Succeeded", file=sys.stderr)
            return 1
        with urllib.request.urlopen(f"{url}/metrics", timeout=10) as resp:
            ctype = resp.headers.get("Content-Type", "")
            text = resp.read().decode()
    finally:
        ctrl.stop()
        kubelet.stop()
        server.stop()

    rc = 0
    if "text/plain" not in ctype:
        print(f"unexpected /metrics content type: {ctype!r}", file=sys.stderr)
        rc = 1
    problems = validate_exposition(text)
    for p in problems:
        print(f"malformed exposition: {p}", file=sys.stderr)
        rc = 1
    for fam in REQUIRED_FAMILIES:
        if f"\n{fam}" not in text and not text.startswith(fam):
            print(f"missing family: {fam}", file=sys.stderr)
            rc = 1
    lines = sum(1 for line in text.splitlines() if line and not line.startswith("#"))
    print(f"metrics-smoke: {lines} samples, "
          f"{len(problems)} problems, rc={rc}")
    return rc


if __name__ == "__main__":
    sys.exit(main())
