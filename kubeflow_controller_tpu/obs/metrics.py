"""Prometheus-style instruments and text exposition.

In-process analog of the client_golang registry the reference never had
(SURVEY.md §5: glog only).  Three instrument kinds — Counter, Gauge,
Histogram — register themselves in a :class:`Registry` whose ``render()``
emits the Prometheus text exposition format (version 0.0.4): ``# HELP`` /
``# TYPE`` headers, escaped label values, cumulative ``_bucket``/``_sum``/
``_count`` histogram series.  Subsystems that keep their own state (e.g.
controller.metrics.ReconcileMetrics) plug in as *collectors* — callables
returning :class:`Family` objects at scrape time.

Get-or-create semantics: asking a registry for an existing metric name
returns the existing instrument (type/labels must match), so components
that are constructed repeatedly in one process (controllers in tests,
multiple workqueues) share series instead of colliding.

``validate_exposition`` is a strict line-level checker used by the
``make metrics-smoke`` target and the test suite.
"""

from __future__ import annotations

import math
import re
from dataclasses import dataclass, field
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

from ..utils import locks

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

# Latency-shaped default buckets: 1ms .. 60s, the range reconcile syncs and
# queue waits actually land in (BASELINE reconcile p50 ~1.2ms; rendezvous
# stalls were ~1s).
DEFAULT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                   0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0)

# Cardinality budget: the most labeled series one instrument may hold.  At
# 10k jobs / 50k pods, object-scoped gauges (per-job step/rate/lag) would
# otherwise grow the /metrics page and the instrument dicts without bound
# if a delete path misses a Gauge.remove.  A new series past the budget is
# DROPPED (not an error — scrapes must keep working mid-storm) and counted
# in kctpu_metric_series_dropped_total{metric} so the loss is observable.
# Existing series keep updating; removes free budget.
DEFAULT_SERIES_BUDGET = 4096


def _series_dropped_counter() -> "Counter":
    """The overflow counter (one labeled series per *instrument*, so its
    own cardinality is bounded by the number of registered metrics)."""
    return REGISTRY.counter(
        "kctpu_metric_series_dropped_total",
        "Label series dropped because an instrument hit its series budget "
        "(cardinality control at scale)", ("metric",))


def escape_label_value(v: str) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def escape_help(h: str) -> str:
    return str(h).replace("\\", "\\\\").replace("\n", "\\n")


def _fmt(v: float) -> str:
    if math.isnan(v):
        return "NaN"
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    return repr(float(v))


@dataclass
class Sample:
    """One exposition line: ``name+suffix{labels} value``."""

    suffix: str
    labels: Dict[str, str]
    value: float


@dataclass
class Family:
    """One metric family: the unit of HELP/TYPE plus its samples."""

    name: str
    typ: str  # counter | gauge | histogram | summary | untyped
    help: str
    samples: List[Sample] = field(default_factory=list)

    def render(self) -> str:
        out = [f"# HELP {self.name} {escape_help(self.help)}",
               f"# TYPE {self.name} {self.typ}"]
        for s in self.samples:
            label_str = ""
            if s.labels:
                inner = ",".join(
                    f'{k}="{escape_label_value(v)}"' for k, v in s.labels.items())
                label_str = "{" + inner + "}"
            out.append(f"{self.name}{s.suffix}{label_str} {_fmt(s.value)}")
        return "\n".join(out)


class _Instrument:
    typ = "untyped"

    def __init__(self, name: str, help: str, labelnames: Sequence[str] = (),
                 max_series: Optional[int] = None):
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        for ln in labelnames:
            if not _LABEL_RE.match(ln) or ln.startswith("__"):
                raise ValueError(f"invalid label name {ln!r} on {name}")
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._max_series = (DEFAULT_SERIES_BUDGET if max_series is None
                            else max_series)
        self._lock = locks.named_lock(f"obs.metric:{name}")

    def _admit(self, table: Dict, key: Tuple[str, ...]) -> bool:
        """Series-budget check (caller holds ``self._lock``): an existing
        key always updates; a NEW key is admitted only under budget."""
        return key in table or len(table) < self._max_series

    def _note_drop(self) -> None:
        """Count one budget-dropped series.  Called with NO lock held (the
        overflow counter is its own instrument — nesting its lock under
        ours would put every instrument pair into one lock-order edge)."""
        if self.name == "kctpu_metric_series_dropped_total":
            return  # the overflow counter never recurses into itself
        _series_dropped_counter().labels(self.name).inc()

    def _key(self, labelvalues: Sequence[str], kv: Dict[str, str]) -> Tuple[str, ...]:
        if kv:
            if labelvalues:
                raise ValueError("pass label values positionally or by name, not both")
            if set(kv) != set(self.labelnames):
                raise ValueError(
                    f"{self.name}: labels {sorted(kv)} != declared {list(self.labelnames)}")
            labelvalues = [kv[ln] for ln in self.labelnames]
        if len(labelvalues) != len(self.labelnames):
            raise ValueError(
                f"{self.name}: got {len(labelvalues)} label values for "
                f"{len(self.labelnames)} labels {list(self.labelnames)}")
        return tuple(str(v) for v in labelvalues)

    def _labels_dict(self, key: Tuple[str, ...]) -> Dict[str, str]:
        return dict(zip(self.labelnames, key))

    def collect(self) -> Family:  # pragma: no cover - overridden
        raise NotImplementedError


class _BoundCounter:
    def __init__(self, parent: "Counter", key: Tuple[str, ...]):
        self._parent = parent
        self._key = key

    def inc(self, amount: float = 1.0) -> None:
        self._parent._inc(self._key, amount)

    @property
    def value(self) -> float:
        with self._parent._lock:
            return self._parent._values.get(self._key, 0.0)


class Counter(_Instrument):
    """Monotonically increasing value; negative increments raise."""

    typ = "counter"

    def __init__(self, name: str, help: str, labelnames: Sequence[str] = (),
                 max_series: Optional[int] = None):
        super().__init__(name, help, labelnames, max_series)
        self._values: Dict[Tuple[str, ...], float] = {}
        if not self.labelnames:
            self._values[()] = 0.0

    def labels(self, *labelvalues, **kv) -> _BoundCounter:
        return _BoundCounter(self, self._key(labelvalues, kv))

    def inc(self, amount: float = 1.0) -> None:
        self._inc(self._key((), {}), amount)

    def _inc(self, key: Tuple[str, ...], amount: float) -> None:
        if amount < 0:
            raise ValueError(f"counter {self.name} cannot decrease (inc {amount})")
        with self._lock:
            if self._admit(self._values, key):
                self._values[key] = self._values.get(key, 0.0) + amount
                return
        self._note_drop()

    def remove(self, *labelvalues, **kv) -> None:
        """Drop one labeled series (no-op if absent) — the counter twin of
        ``Gauge.remove``.  Object-scoped counters (e.g. the goodput
        ledger's per-job badput buckets) call this on object delete so
        the exposition page doesn't strand dead series; scrapers must
        treat the disappearance like a counter reset (rate() already
        clamps resets to zero, obs/tsdb.py)."""
        key = self._key(labelvalues, kv)
        with self._lock:
            self._values.pop(key, None)

    @property
    def value(self) -> float:
        with self._lock:
            return self._values.get((), 0.0)

    def collect(self) -> Family:
        with self._lock:
            items = sorted(self._values.items())
        return Family(self.name, self.typ, self.help, [
            Sample("", self._labels_dict(k), v) for k, v in items])


class _BoundGauge:
    def __init__(self, parent: "Gauge", key: Tuple[str, ...]):
        self._parent = parent
        self._key = key

    def set(self, v: float) -> None:
        self._parent._set(self._key, v)

    def inc(self, amount: float = 1.0) -> None:
        self._parent._add(self._key, amount)

    def dec(self, amount: float = 1.0) -> None:
        self._parent._add(self._key, -amount)

    def set_function(self, fn: Callable[[], float]) -> None:
        self._parent._set_fn(self._key, fn)

    @property
    def value(self) -> float:
        with self._parent._lock:
            fn = self._parent._fns.get(self._key)
            if fn is not None:
                return float(fn())
            return self._parent._values.get(self._key, 0.0)


class Gauge(_Instrument):
    """Settable value; optionally backed by a callback sampled at scrape."""

    typ = "gauge"

    def __init__(self, name: str, help: str, labelnames: Sequence[str] = (),
                 max_series: Optional[int] = None):
        super().__init__(name, help, labelnames, max_series)
        self._values: Dict[Tuple[str, ...], float] = {}
        self._fns: Dict[Tuple[str, ...], Callable[[], float]] = {}
        if not self.labelnames:
            self._values[()] = 0.0

    def labels(self, *labelvalues, **kv) -> _BoundGauge:
        return _BoundGauge(self, self._key(labelvalues, kv))

    def set(self, v: float) -> None:
        self._set((), v)

    def inc(self, amount: float = 1.0) -> None:
        self._add((), amount)

    def dec(self, amount: float = 1.0) -> None:
        self._add((), -amount)

    def set_function(self, fn: Callable[[], float]) -> None:
        self._set_fn((), fn)

    def _set(self, key: Tuple[str, ...], v: float) -> None:
        with self._lock:
            if self._admit(self._values, key):
                self._values[key] = float(v)
                return
        self._note_drop()

    def _add(self, key: Tuple[str, ...], amount: float) -> None:
        with self._lock:
            if self._admit(self._values, key):
                self._values[key] = self._values.get(key, 0.0) + amount
                return
        self._note_drop()

    def _set_fn(self, key: Tuple[str, ...], fn: Callable[[], float]) -> None:
        with self._lock:
            if self._admit(self._values, key) or key in self._fns:
                self._fns[key] = fn
                self._values.setdefault(key, 0.0)
                return
        self._note_drop()

    def remove(self, *labelvalues, **kv) -> None:
        """Drop one labeled series (no-op if absent).  Object-scoped gauges
        (e.g. per-job progress) call this when the object is deleted, so
        the exposition page doesn't accumulate one dead series per job
        ever run."""
        key = self._key(labelvalues, kv)
        with self._lock:
            self._values.pop(key, None)
            self._fns.pop(key, None)

    @property
    def value(self) -> float:
        return _BoundGauge(self, ()).value

    def collect(self) -> Family:
        with self._lock:
            keys = sorted(set(self._values) | set(self._fns))
            fns = dict(self._fns)
            values = dict(self._values)
        samples = []
        for k in keys:
            fn = fns.get(k)
            try:
                v = float(fn()) if fn is not None else values.get(k, 0.0)
            except Exception:
                v = values.get(k, 0.0)  # a dead callback must not break scrape
            samples.append(Sample("", self._labels_dict(k), v))
        return Family(self.name, self.typ, self.help, samples)


class _HistState:
    __slots__ = ("counts", "sum", "count")

    def __init__(self, n_buckets: int):
        self.counts = [0] * n_buckets  # per-bucket (non-cumulative) counts
        self.sum = 0.0
        self.count = 0


class _BoundHistogram:
    def __init__(self, parent: "Histogram", key: Tuple[str, ...]):
        self._parent = parent
        self._key = key

    def observe(self, v: float) -> None:
        self._parent._observe(self._key, v)

    @property
    def count(self) -> int:
        with self._parent._lock:
            st = self._parent._states.get(self._key)
            return st.count if st else 0

    @property
    def sum(self) -> float:
        with self._parent._lock:
            st = self._parent._states.get(self._key)
            return st.sum if st else 0.0


class Histogram(_Instrument):
    """Cumulative-bucket histogram (``le`` upper bounds, +Inf implicit)."""

    typ = "histogram"

    def __init__(self, name: str, help: str, labelnames: Sequence[str] = (),
                 buckets: Sequence[float] = DEFAULT_BUCKETS,
                 max_series: Optional[int] = None):
        super().__init__(name, help, labelnames, max_series)
        bs = sorted(float(b) for b in buckets)
        if not bs:
            raise ValueError(f"{name}: need at least one bucket")
        if math.isinf(bs[-1]):
            bs = bs[:-1]  # +Inf is implicit
        self.buckets = tuple(bs)
        self._states: Dict[Tuple[str, ...], _HistState] = {}
        if not self.labelnames:
            self._states[()] = _HistState(len(self.buckets) + 1)

    def labels(self, *labelvalues, **kv) -> _BoundHistogram:
        return _BoundHistogram(self, self._key(labelvalues, kv))

    def observe(self, v: float) -> None:
        self._observe(self._key((), {}), v)

    def _observe(self, key: Tuple[str, ...], v: float) -> None:
        v = float(v)
        i = len(self.buckets)  # +Inf slot
        for j, b in enumerate(self.buckets):
            if v <= b:
                i = j
                break
        with self._lock:
            st = self._states.get(key)
            if st is None:
                if not self._admit(self._states, key):
                    st = None
                else:
                    st = self._states[key] = _HistState(len(self.buckets) + 1)
            if st is not None:
                st.counts[i] += 1
                st.sum += v
                st.count += 1
                return
        self._note_drop()

    @property
    def count(self) -> int:
        return _BoundHistogram(self, ()).count

    @property
    def sum(self) -> float:
        return _BoundHistogram(self, ()).sum

    def collect(self) -> Family:
        with self._lock:
            snap = {k: (list(st.counts), st.sum, st.count)
                    for k, st in sorted(self._states.items())}
        samples = []
        for k, (counts, total, count) in snap.items():
            base = self._labels_dict(k)
            acc = 0
            for b, c in zip(self.buckets, counts):
                acc += c
                samples.append(Sample("_bucket", {**base, "le": _fmt(b)}, acc))
            samples.append(Sample("_bucket", {**base, "le": "+Inf"}, count))
            samples.append(Sample("_sum", base, total))
            samples.append(Sample("_count", base, count))
        return Family(self.name, self.typ, self.help, samples)


class Registry:
    """Named instruments + pluggable collectors, rendered as one page."""

    def __init__(self):
        self._lock = locks.named_lock("obs.registry")
        self._metrics: Dict[str, _Instrument] = {}
        self._collectors: Dict[str, Callable[[], Iterable[Family]]] = {}

    # -- get-or-create instruments -------------------------------------------

    def _get_or_create(self, cls, name: str, help: str,
                       labelnames: Sequence[str], **kw):
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if not isinstance(existing, cls) or existing.labelnames != tuple(labelnames):
                    raise ValueError(
                        f"metric {name} already registered as "
                        f"{type(existing).__name__}{existing.labelnames}")
                return existing
            m = cls(name, help, labelnames, **kw)
            self._metrics[name] = m
            return m

    def counter(self, name: str, help: str,
                labelnames: Sequence[str] = (),
                max_series: Optional[int] = None) -> Counter:
        return self._get_or_create(Counter, name, help, labelnames,
                                   max_series=max_series)

    def gauge(self, name: str, help: str,
              labelnames: Sequence[str] = (),
              max_series: Optional[int] = None) -> Gauge:
        return self._get_or_create(Gauge, name, help, labelnames,
                                   max_series=max_series)

    def histogram(self, name: str, help: str, labelnames: Sequence[str] = (),
                  buckets: Sequence[float] = DEFAULT_BUCKETS,
                  max_series: Optional[int] = None) -> Histogram:
        return self._get_or_create(Histogram, name, help, labelnames,
                                   buckets=buckets, max_series=max_series)

    # -- collectors ----------------------------------------------------------

    def register_collector(self, key: str,
                           fn: Callable[[], Iterable[Family]]) -> None:
        """Register (or replace — same key) a scrape-time family producer.
        Keyed replacement keeps repeatedly-constructed components (a new
        Controller per test) from stacking duplicate families."""
        with self._lock:
            self._collectors[key] = fn

    def unregister_collector(self, key: str) -> None:
        with self._lock:
            self._collectors.pop(key, None)

    # -- exposition ----------------------------------------------------------

    def families(self) -> List[Family]:
        with self._lock:
            metrics = list(self._metrics.values())
            collectors = list(self._collectors.values())
        fams = [m.collect() for m in metrics]
        for fn in collectors:
            try:
                fams.extend(fn())
            except Exception:
                continue  # one broken collector must not break the scrape
        return sorted(fams, key=lambda f: f.name)

    def render(self) -> str:
        return "\n".join(f.render() for f in self.families()) + "\n"

    def histogram_quantile(self, name: str, labels: Dict[str, str],
                           q: float) -> float:
        """Scrape-time quantile from a live histogram's cumulative buckets
        (``bucket_quantile`` estimate, all-time distribution).  ``labels``
        selects one series, ``le`` excluded; 0.0 when the family or series
        does not exist — callers can fall back to a raw gauge."""
        fam = None
        for f in self.families():
            if f.name == name and f.typ == "histogram":
                fam = f
                break
        if fam is None:
            return 0.0
        want = dict(labels)
        uppers: List[float] = []
        cumulative: List[float] = []
        for s in fam.samples:
            if s.suffix != "_bucket":
                continue
            have = {k: v for k, v in s.labels.items() if k != "le"}
            if have != want:
                continue
            le = s.labels.get("le", "")
            if le == "+Inf":
                cumulative.append(s.value)
            else:
                uppers.append(float(le))
                cumulative.append(s.value)
        if not uppers or len(cumulative) != len(uppers) + 1:
            return 0.0
        counts = [cumulative[0]] + [
            max(0.0, cumulative[i] - cumulative[i - 1])
            for i in range(1, len(cumulative))]
        return bucket_quantile(uppers, counts, q)


def bucket_quantile(uppers: Sequence[float], counts: Sequence[int],
                    q: float) -> float:
    """Conservative quantile estimate from per-bucket (non-cumulative)
    counts: the upper bound of the bucket the ``q``-th sample falls in
    (``uppers[-1]`` doubled for the +Inf overflow slot).  ``counts`` has
    ``len(uppers) + 1`` entries, the last being the overflow bucket.
    Returns 0.0 with no samples."""
    total = sum(counts)
    if total <= 0:
        return 0.0
    rank = q * total
    acc = 0.0
    for i, c in enumerate(counts):
        acc += c
        if acc >= rank:
            return float(uppers[i]) if i < len(uppers) else float(uppers[-1]) * 2
    return float(uppers[-1]) * 2


#: Process-global default registry — what ``GET /metrics`` serves.
REGISTRY = Registry()


# ---------------------------------------------------------------------------
# Exposition validation (make metrics-smoke / tests)
# ---------------------------------------------------------------------------

_COMMENT_RE = re.compile(r"^# (HELP|TYPE) ([a-zA-Z_:][a-zA-Z0-9_:]*)( (.*))?$")
_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>[^}]*)\})?"
    r" (?P<value>[^ ]+)"
    r"( (?P<ts>-?[0-9]+))?$")
_LABEL_PAIR_RE = re.compile(
    r'^[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\\n]|\\\\|\\"|\\n)*"$')
_TYPES = {"counter", "gauge", "histogram", "summary", "untyped"}
_SUFFIXES = ("_bucket", "_sum", "_count", "_total")


def _base_name(sample_name: str, typed: Dict[str, str]) -> str:
    if sample_name in typed:
        return sample_name
    for suf in _SUFFIXES:
        if sample_name.endswith(suf) and sample_name[: -len(suf)] in typed:
            return sample_name[: -len(suf)]
    return sample_name


def validate_exposition(text: str) -> List[str]:
    """Line-level structural validation of Prometheus text exposition.
    Returns a list of problems (empty == valid)."""
    problems: List[str] = []
    typed: Dict[str, str] = {}
    seen_series: set = set()
    for i, line in enumerate(text.split("\n"), 1):
        if line == "":
            continue
        if line.startswith("#"):
            m = _COMMENT_RE.match(line)
            if m is None:
                if line.startswith(("# HELP", "# TYPE")):
                    problems.append(f"line {i}: malformed comment: {line!r}")
                continue  # plain comments are legal
            if m.group(1) == "TYPE":
                typ = (m.group(4) or "").strip()
                if typ not in _TYPES:
                    problems.append(f"line {i}: unknown TYPE {typ!r}")
                if m.group(2) in typed:
                    problems.append(f"line {i}: duplicate TYPE for {m.group(2)}")
                typed[m.group(2)] = typ
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            problems.append(f"line {i}: unparseable sample: {line!r}")
            continue
        raw_labels = m.group("labels")
        labels = {}
        if raw_labels:
            # Split on commas outside quotes.
            parts, depth, cur = [], False, ""
            prev = ""
            for ch in raw_labels:
                if ch == '"' and prev != "\\":
                    depth = not depth
                if ch == "," and not depth:
                    parts.append(cur)
                    cur = ""
                else:
                    cur += ch
                prev = ch
            if cur:
                parts.append(cur)
            for p in parts:
                if not _LABEL_PAIR_RE.match(p.strip()):
                    problems.append(f"line {i}: malformed label pair {p!r}")
                    continue
                k, v = p.strip().split("=", 1)
                labels[k] = v
        val = m.group("value")
        if val not in ("+Inf", "-Inf", "NaN"):
            try:
                float(val)
            except ValueError:
                problems.append(f"line {i}: bad value {val!r}")
        base = _base_name(m.group("name"), typed)
        if base not in typed and m.group("name") not in typed:
            problems.append(f"line {i}: sample {m.group('name')} has no TYPE")
        series = (m.group("name"), tuple(sorted(labels.items())))
        if series in seen_series:
            problems.append(f"line {i}: duplicate series {series[0]}{dict(labels)}")
        seen_series.add(series)
        typ = typed.get(base)
        if typ == "histogram" and m.group("name") == base + "_bucket" and "le" not in labels:
            problems.append(f"line {i}: histogram bucket without le label")
    return problems
