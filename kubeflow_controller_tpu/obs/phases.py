"""The phase vocabulary, in one place.

Two consumers grew the same vocabulary piecemeal across PRs and this
module is their single source of truth:

1. **Beat phases** — the coarse workload phase a ``PodProgress`` beat
   carries (``workloads/progress.py``).  The stall detector
   (``checker/health.py``) holds the frozen-step deadline for a subset
   of them (a long XLA compile or checkpoint restore beats with a frozen
   step counter on purpose); before this registry the hold list was a
   hardcoded tuple that silently lost protection on a typo'd phase.
   The ``phase-registry`` vet rule (analysis/vet.py) now flags any
   ``phase="..."`` literal unknown to :data:`KNOWN_PHASES`.

2. **Ledger buckets** — the closed taxonomy the goodput ledger
   (``obs/goodput.py``) attributes every second of a replica's lifetime
   to.  Beat phases map into buckets via :func:`bucket_for_beat_phase`;
   control-plane states (queue-wait, scheduling, preemption, terminal)
   have buckets of their own with no beat-phase counterpart.

The pod-reason prefixes the capacity plane stamps on Pending/Failed
pods (scheduler, elastic engine) also live here: the ledger, the status
updater, the CLI, and the recovery policy all sniff them, and obs/ is
the one leaf package everything above may import.
"""

from __future__ import annotations

from typing import Tuple

# ---------------------------------------------------------------------------
# Beat phases (PodProgress.phase) — what a workload says it is doing.
# ---------------------------------------------------------------------------

PHASE_RENDEZVOUS = "rendezvous"   # jax.distributed barrier / gang join
PHASE_INIT = "init"               # pre-step setup after rendezvous
PHASE_COMPILE = "compile"         # XLA compile (TTFS pipeline)
PHASE_FIT = "fit"                 # training step loop — THE goodput phase
PHASE_RESTORE = "restore"         # checkpoint restore on (re)start
PHASE_RESHARD = "reshard"         # elastic width transition
PHASE_LOAD = "load"               # serving model load
PHASE_SERVING = "serving"         # serving decode loop — serving goodput
PHASE_DRAIN = "drain"             # serving graceful drain

# Every phase a beat may carry ("" = reporter did not say; treated as
# fit-adjacent by consumers that must pick something).
KNOWN_PHASES = frozenset({
    PHASE_RENDEZVOUS, PHASE_INIT, PHASE_COMPILE, PHASE_FIT, PHASE_RESTORE,
    PHASE_RESHARD, PHASE_LOAD, PHASE_SERVING, PHASE_DRAIN, "",
})

# Phases that hold the stall detector's frozen-step deadline: the step
# counter legitimately freezes while these run (the heartbeat deadline
# always applies regardless).  Grown across PRs 8/9/13/15; now the
# StallTracker imports this instead of a private tuple.
STALL_HOLD_PHASES = frozenset({
    PHASE_COMPILE, PHASE_RESTORE, PHASE_RESHARD, PHASE_LOAD,
    PHASE_SERVING, PHASE_DRAIN,
})

# ---------------------------------------------------------------------------
# Pod-reason prefixes — the capacity plane's verdicts, stamped as pod
# status reasons so they work in any deployment shape.  Stampers:
# scheduler/scheduler.py, elastic/engine.py.  Sniffers: updater/status.py,
# controller/controller.py, recovery/policy.py, cli/main.py, obs/goodput.py.
# ---------------------------------------------------------------------------

POD_REASON_QUEUED_PREFIX = "GangQueued"        # Pending: gang waiting in queue
POD_REASON_PREEMPTED_PREFIX = "Preempted"      # Failed: higher class took slices
POD_REASON_HARVESTED_PREFIX = "WidthHarvested"  # Failed: elastic width harvest

# ---------------------------------------------------------------------------
# Ledger buckets (obs/goodput.py) — the closed attribution taxonomy.
# Every second of a replica's lifetime lands in exactly one of these.
# ---------------------------------------------------------------------------

BUCKET_QUEUED = "queued"               # gang waiting for slices (scheduler queue)
BUCKET_SCHEDULING = "scheduling"       # Pending, not queue-blocked (bind/admit)
BUCKET_STARTING_COLD = "starting_cold"  # Running, pre-first-beat, cold start
BUCKET_STARTING_WARM = "starting_warm"  # Running, pre-first-beat, warm readmit
BUCKET_RENDEZVOUS = "rendezvous"       # gang join + init
BUCKET_COMPILE_CACHED = "compile_cached"  # compile resolved from the cache
BUCKET_COMPILE_MISS = "compile_miss"   # compile actually compiled
BUCKET_RESTORE = "restore"             # checkpoint restore
BUCKET_TRAIN = "train"                 # step loop — training goodput
BUCKET_SERVING = "serving"             # decode loop — serving goodput
BUCKET_STALLED = "stalled"             # stall detector's verdict overrides beats
BUCKET_RESHARD = "reshard"             # elastic width transition
BUCKET_PREEMPTED = "preempted"         # killed by a higher priority class
BUCKET_HARVESTED = "harvested"         # width harvested by the scheduler
BUCKET_DRAIN = "drain"                 # serving graceful drain
BUCKET_TERMINAL = "terminal"           # Succeeded/Failed tail until observed

ALL_BUCKETS: Tuple[str, ...] = (
    BUCKET_QUEUED, BUCKET_SCHEDULING, BUCKET_STARTING_COLD,
    BUCKET_STARTING_WARM, BUCKET_RENDEZVOUS, BUCKET_COMPILE_CACHED,
    BUCKET_COMPILE_MISS, BUCKET_RESTORE, BUCKET_TRAIN, BUCKET_SERVING,
    BUCKET_STALLED, BUCKET_RESHARD, BUCKET_PREEMPTED, BUCKET_HARVESTED,
    BUCKET_DRAIN, BUCKET_TERMINAL,
)

# The only buckets that count as goodput.  Everything else is badput —
# except the non-occupied buckets below, which are excluded from the
# ratio's denominator entirely (queue-wait is the scheduler's debt, not
# the job's, and it would drown the signal for a long-queued job).
GOODPUT_BUCKETS: Tuple[str, ...] = (BUCKET_TRAIN, BUCKET_SERVING)

# Buckets excluded from the goodput ratio denominator: the replica is
# not occupying accelerator resources (or is past caring).
NON_OCCUPIED_BUCKETS: Tuple[str, ...] = (
    BUCKET_QUEUED, BUCKET_SCHEDULING, BUCKET_TERMINAL,
)

# Beat phase -> ledger bucket for a Running replica that is beating.
_BEAT_BUCKET = {
    PHASE_RENDEZVOUS: BUCKET_RENDEZVOUS,
    PHASE_INIT: BUCKET_RENDEZVOUS,
    PHASE_COMPILE: BUCKET_COMPILE_MISS,   # re-attributed on cache-hit, see below
    PHASE_FIT: BUCKET_TRAIN,
    PHASE_RESTORE: BUCKET_RESTORE,
    PHASE_RESHARD: BUCKET_RESHARD,
    PHASE_LOAD: BUCKET_RESTORE,           # model load = restore-shaped badput
    PHASE_SERVING: BUCKET_SERVING,
    PHASE_DRAIN: BUCKET_DRAIN,
}

# compile_source value that marks a cache-served executable
# (workloads/progress.py TTFS pipeline).
COMPILE_SOURCE_CACHE_HIT = "cache-hit"
COMPILE_SOURCE_COMPILED = "compiled"


def bucket_for_beat_phase(phase: str, compile_source: str = "") -> str:
    """Ledger bucket for a Running, beating replica.

    Attribution rules at the boundaries (documented in OBSERVABILITY.md):

    - ``compile`` accrues into ``compile_miss`` while in flight; once the
      beat reports ``compile_source == "cache-hit"`` the ledger
      re-attributes the accrued compile time to ``compile_cached`` (the
      provenance only resolves when the compile phase does).
    - ``load`` (serving model load) lands in ``restore`` — same shape of
      badput: reading bytes before useful work.
    - An empty/unknown phase on a beating replica counts as ``train``
      (serving replicas always report a phase, so unknown == training
      step loop that predates phase reporting).
    """
    if phase == PHASE_COMPILE and compile_source == COMPILE_SOURCE_CACHE_HIT:
        return BUCKET_COMPILE_CACHED
    return _BEAT_BUCKET.get(phase, BUCKET_TRAIN)
