"""`make slo-smoke`: the SLO burn-rate pipeline end-to-end, plus the
trace-continuity gate.

Part A — burn fire -> resolve, through the REAL pipeline (no shortcuts:
beats -> status rollup -> registry gauge -> TSDB sample -> burn eval ->
event + alert gauges):

1. boot the in-process cluster + controller, start the obs plane with a
   compressed serving-ttft-p99 objective (sub-second windows);
2. run one Serving job; its replica beats a throttled p99 TTFT (5s,
   2.5x over the 2s threshold) — within a few window lengths EXACTLY ONE
   ``Warning SLOBurn`` must fire, with ``kctpu_slo_alert_active=1`` on
   ``GET /metrics`` and an active alert on ``GET /debug/slos``;
3. the replica recovers (80 ms TTFT) — ``Normal SLORecovered`` must
   follow, the gauge must drop to 0, and the engine must have seen
   exactly one fire edge (no flapping).

Part B — trace continuity: the job's causal trace (obs/trace.py) must
exist, carry a single trace_id, span the submit->sync->kubelet chain,
and contain ZERO orphan spans (every parent_id resolves).

Exit 0 = burn alerting is edge-exact and causal traces are connected.
"""

from __future__ import annotations

import re
import sys
import time
import urllib.request


def _scrape_alert_active(url: str) -> float:
    with urllib.request.urlopen(f"{url}/metrics", timeout=10) as resp:
        text = resp.read().decode()
    pat = re.compile(
        r'^kctpu_slo_alert_active\{[^}]*slo="serving-ttft-p99"[^}]*\} (\S+)$',
        re.M)
    vals = [float(m.group(1)) for m in pat.finditer(text)]
    return max(vals) if vals else -1.0


def main() -> int:
    from ..api.core import Container, PodProgress, PodTemplateSpec
    from ..api.meta import ObjectMeta
    from ..api.tfjob import ReplicaType, TFJob, TFReplicaSpec
    from ..cluster import Cluster, FakeKubelet, PhasePolicy
    from ..cluster.apiserver import FakeAPIServer
    from ..controller import Controller
    from . import trace
    from .slo import Objective, default_slo_engine

    # Compressed objective: same shape as the catalogue's serving-ttft-p99
    # (docs/OBSERVABILITY.md), windows shrunk so the smoke runs in seconds.
    default_slo_engine().set_objectives([Objective(
        name="serving-ttft-p99",
        description="worst-replica p99 time-to-first-token <= 2s",
        metric="kctpu_serve_ttft_p99_ms", threshold=2000.0,
        error_budget=0.05, fast_window_s=0.6, slow_window_s=1.5,
        burn_threshold=2.0)])

    cluster = Cluster()
    server = FakeAPIServer(cluster.store)
    url = server.start()
    kubelet = FakeKubelet(cluster, policy=PhasePolicy(run_s=300.0))
    ctrl = Controller(cluster, resync_period_s=5.0)
    ctrl.start_obs_plane(interval_s=0.1)
    kubelet.start()
    ctrl.run(threadiness=2)
    rc = 1
    try:
        job = TFJob(metadata=ObjectMeta(name="slo-svc", namespace="default"))
        t = PodTemplateSpec()
        t.spec.containers.append(Container(name="srv", image="img"))
        t.spec.restart_policy = "OnFailure"
        job.spec.tf_replica_specs.append(TFReplicaSpec(
            replicas=1, tf_replica_type=ReplicaType.SERVING, template=t))
        cluster.tfjobs.create(job)

        def wait_for(cond, what: str, timeout: float = 20.0) -> bool:
            deadline = time.time() + timeout
            while time.time() < deadline:
                if cond():
                    return True
                time.sleep(0.05)
            print(f"slo-smoke: timed out waiting for {what}", file=sys.stderr)
            return False

        def serving_pod():
            for p in cluster.pods.list("default"):
                if (p.metadata.name.startswith("slo-svc-serving-")
                        and p.status.phase == "Running"):
                    return p
            return None

        def has_event(reason: str) -> int:
            return sum(1 for e in ctrl.recorder.events_for("default", "slo-svc")
                       if e.reason == reason)

        if not wait_for(lambda: serving_pod() is not None,
                        "the serving replica to reach Running"):
            return 1
        pod_name = serving_pod().metadata.name

        # Throttled replica: p99 TTFT 2.5x over threshold, beating steadily.
        stop_beats = [False]
        ttft = [5000.0]

        import threading

        def beater():
            while not stop_beats[0]:
                cluster.pods.update_progress(
                    "default", pod_name,
                    PodProgress(step=10, phase="serving", qps=2.0,
                                ttft_ms=ttft[0] / 10, ttft_p99_ms=ttft[0],
                                slots_used=2, slots_total=4))
                time.sleep(0.05)

        th = threading.Thread(target=beater, name="slo-smoke-beater",
                              daemon=True)
        th.start()

        if not wait_for(lambda: has_event("SLOBurn") >= 1,
                        "Warning SLOBurn event"):
            return 1
        if not wait_for(lambda: _scrape_alert_active(url) == 1.0,
                        "kctpu_slo_alert_active=1 on /metrics"):
            return 1

        # Recovery: the replica gets fast again.
        ttft[0] = 80.0
        if not wait_for(lambda: has_event("SLORecovered") >= 1,
                        "Normal SLORecovered event"):
            return 1
        if not wait_for(lambda: _scrape_alert_active(url) == 0.0,
                        "kctpu_slo_alert_active=0 on /metrics"):
            return 1
        stop_beats[0] = True
        th.join(timeout=2)

        # Edge exactness: exactly one fire, one recovery, one transition.
        burns, recovers = has_event("SLOBurn"), has_event("SLORecovered")
        if burns != 1 or recovers != 1:
            print(f"slo-smoke: expected exactly 1 fire + 1 resolve, got "
                  f"{burns} SLOBurn / {recovers} SLORecovered",
                  file=sys.stderr)
            return 1
        states = default_slo_engine().alerts(active_only=False)
        mine = [s for s in states if s["slo"] == "serving-ttft-p99"
                and s["labels"].get("tfjob") == "slo-svc"]
        if not mine or mine[0]["transitions"] != 1:
            print(f"slo-smoke: expected exactly 1 engine fire edge, "
                  f"state={mine}", file=sys.stderr)
            return 1

        # Part B: trace continuity.  The job's causal tree must exist,
        # share one trace_id, and resolve every parent edge.
        events = [s.to_event() for s in trace.TRACER.spans()]
        root_trace = ""
        for e in events:
            a = e.get("args") or {}
            if a.get("job") == "slo-svc" and trace.event_ids(e)[0]:
                root_trace = trace.event_ids(e)[0]
                break
        if not root_trace:
            print("slo-smoke: no causal trace recorded for the job",
                  file=sys.stderr)
            return 1
        mine_events = trace.events_for_trace(events, root_trace)
        orphans = trace.orphan_events(mine_events)
        if len(mine_events) < 3 or orphans:
            print(f"slo-smoke: broken causal trace — {len(mine_events)} "
                  f"spans, {len(orphans)} orphan(s)", file=sys.stderr)
            return 1

        print(f"slo-smoke: 1 SLOBurn -> 1 SLORecovered (edge-exact), "
              f"alert gauge 1 -> 0 | trace {root_trace}: "
              f"{len(mine_events)} spans, 0 orphans")
        rc = 0
    finally:
        ctrl.stop()
        kubelet.stop()
        server.stop()
    return rc


if __name__ == "__main__":
    sys.exit(main())
