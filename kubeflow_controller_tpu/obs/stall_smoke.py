"""`make stall-smoke`: kill heartbeats in a simulated run, assert the
stall pipeline fires end-to-end, then assert it recovers.

Boots the in-process cluster with simulated training heartbeats
(``PhasePolicy.heartbeat_s``), runs a 2-worker job, then:

1. suspends the kubelet's heartbeats (what a hung training process looks
   like from the control plane) and asserts, within the stall deadline,
   a ``Warning TrainingStalled`` event and ``kctpu_job_stalled=1`` on the
   HTTP ``GET /metrics`` page;
2. resumes heartbeats and asserts ``Normal TrainingResumed`` and
   ``kctpu_job_stalled=0``.

Exit 0 = the progress plane detects and clears stalls for real.
"""

from __future__ import annotations

import re
import sys
import time
import urllib.request


def _scrape_stalled(url: str, ns: str, name: str) -> float:
    with urllib.request.urlopen(f"{url}/metrics", timeout=10) as resp:
        text = resp.read().decode()
    pat = re.compile(
        rf'^kctpu_job_stalled\{{namespace="{ns}",tfjob="{name}"\}} (\S+)$',
        re.M)
    m = pat.search(text)
    return float(m.group(1)) if m else -1.0


def main() -> int:
    from ..api.core import Container, PodTemplateSpec
    from ..api.meta import ObjectMeta
    from ..api.tfjob import ReplicaType, TFJob, TFReplicaSpec
    from ..checker import StallPolicy
    from ..cluster import Cluster, FakeKubelet, PhasePolicy
    from ..cluster.apiserver import FakeAPIServer
    from ..controller import Controller

    cluster = Cluster()
    server = FakeAPIServer(cluster.store)
    url = server.start()
    # Long-running simulated workers beating every 50 ms; heartbeat silence
    # past 0.4 s is a stall.  Step-deadline off: frozen heartbeats are the
    # injected failure mode here.
    kubelet = FakeKubelet(cluster, policy=PhasePolicy(run_s=120.0,
                                                      heartbeat_s=0.05))
    ctrl = Controller(cluster, resync_period_s=5.0,
                      stall_policy=StallPolicy(heartbeat_deadline_s=0.4,
                                               step_deadline_s=0.0,
                                               check_interval_s=0.1))
    kubelet.start()
    ctrl.run(threadiness=2)
    rc = 1
    try:
        job = TFJob(metadata=ObjectMeta(name="stall-smoke", namespace="default"))
        t = PodTemplateSpec()
        t.spec.containers.append(Container(name="tensorflow", image="img"))
        t.spec.restart_policy = "OnFailure"
        job.spec.tf_replica_specs.append(
            TFReplicaSpec(replicas=2, tf_replica_type=ReplicaType.WORKER,
                          template=t))
        cluster.tfjobs.create(job)

        def wait_for(cond, what: str, timeout: float = 20.0) -> bool:
            deadline = time.time() + timeout
            while time.time() < deadline:
                if cond():
                    return True
                time.sleep(0.05)
            print(f"stall-smoke: timed out waiting for {what}", file=sys.stderr)
            return False

        def job_progress():
            p = cluster.tfjobs.get("default", "stall-smoke").status.progress
            return p is not None and p.step > 0

        def has_event(reason: str) -> bool:
            return any(e.reason == reason
                       for e in ctrl.recorder.events_for("default", "stall-smoke"))

        if not wait_for(job_progress, "heartbeats to reach job status"):
            return 1
        kubelet.suspend_heartbeats()
        if not wait_for(lambda: has_event("TrainingStalled"),
                        "Warning TrainingStalled event"):
            return 1
        if not wait_for(lambda: _scrape_stalled(url, "default", "stall-smoke") == 1.0,
                        "kctpu_job_stalled=1 on /metrics"):
            return 1
        kubelet.resume_heartbeats()
        if not wait_for(lambda: has_event("TrainingResumed"),
                        "Normal TrainingResumed event"):
            return 1
        if not wait_for(lambda: _scrape_stalled(url, "default", "stall-smoke") == 0.0,
                        "kctpu_job_stalled=0 on /metrics"):
            return 1
        print("stall-smoke: stall detected and cleared "
              "(TrainingStalled -> TrainingResumed, gauge 1 -> 0)")
        rc = 0
    finally:
        ctrl.stop()
        kubelet.stop()
        server.stop()
    return rc


if __name__ == "__main__":
    sys.exit(main())
