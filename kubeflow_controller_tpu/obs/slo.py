"""SLO engine: declarative objectives + multi-window burn-rate alerting.

An objective names a retained series (or histogram family) in the TSDB
(obs/tsdb.py), a violation threshold, and an error budget — "serving p99
TTFT stays under 2 s, with at most 5% of samples over".  Evaluation is
the Google-SRE multi-window burn rate:

- **burn** of a window = (fraction of the window's samples violating the
  threshold) / error budget, so burn 1.0 consumes budget exactly at the
  allowed pace and burn 20 eats a 5%-budget objective 20x too fast;
- an alert **fires** when BOTH the fast and the slow window burn at or
  above ``burn_threshold`` (fast = reacts quickly, slow = proves it is
  not a blip), and **resolves** when the fast window falls back under —
  edge-triggered, exactly one notification per transition.

State lands in three places: ``kctpu_slo_burn_rate`` /
``kctpu_slo_alert_active`` gauges on the registry, edge-triggered
``Warning SLOBurn`` / ``Normal SLORecovered`` events (via the notifier
the controller installs), and the queryable :meth:`SLOEngine.state`
served at ``GET /debug/slos`` for ``kctpu alerts`` and the ``kctpu get``
banner.

Objectives over *labeled* series fan out per label set (one alert per
job), so the notifier can attach events to the job that breached.

Like the rest of obs/, this imports nothing above obs/: the controller
hands in its recorder via a notifier callback, and evaluation is driven
either by the TSDB's sampler (:meth:`TSDB.add_listener`) or explicitly
(``evaluate_once(now)`` — the testable unit)."""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..utils import locks
from .metrics import REGISTRY, Registry
from .tsdb import TSDB

# Objective kinds.
KIND_GAUGE = "gauge"                    # violating-sample fraction of a series
KIND_HISTOGRAM_QUANTILE = "histogram_quantile"  # windowed quantile vs threshold

# Violation directions.
DIRECTION_ABOVE = "above"  # value > threshold violates (latency, depth)
DIRECTION_BELOW = "below"  # value < threshold violates (goodput ratio)


@dataclass(frozen=True)
class Objective:
    """One declarative SLO."""

    name: str                  # slug: "serving-ttft-p99"
    description: str
    metric: str                # series name (gauge) or histogram family
    threshold: float           # a sample/quantile past this is a violation
    kind: str = KIND_GAUGE
    q: float = 0.99            # histogram_quantile only
    error_budget: float = 0.05  # allowed violating fraction
    fast_window_s: float = 30.0
    slow_window_s: float = 120.0
    burn_threshold: float = 2.0
    # Which side of ``threshold`` violates: "above" (latency-style, the
    # default) or "below" (ratio-style — the goodput objectives fire when
    # the ratio DROPS under the floor).
    direction: str = DIRECTION_ABOVE
    # Label keys identifying who breached (event routing); objectives fan
    # out over every label set the TSDB retains for ``metric``.
    subject_labels: Tuple[str, ...] = ("namespace", "tfjob")

    def violates(self, value: float) -> bool:
        if self.direction == DIRECTION_BELOW:
            return value < self.threshold
        return value > self.threshold


def default_objectives() -> List[Objective]:
    """The catalogue (docs/OBSERVABILITY.md "SLO catalogue"): serving p99
    TTFT, job time-to-first-step, training stall rate, failover (gang
    replacement) time, and scheduler queue wait."""
    return [
        Objective(
            name="serving-ttft-p99",
            description="worst-replica p99 time-to-first-token <= 2s",
            metric="kctpu_serve_ttft_p99_ms", threshold=2000.0,
            error_budget=0.05),
        Objective(
            name="job-ttfs",
            description="p99 job time-to-first-step (Created->Running) <= 120s",
            metric="kctpu_job_phase_transition_seconds", threshold=120.0,
            kind=KIND_HISTOGRAM_QUANTILE, q=0.99, error_budget=0.05,
            subject_labels=("from_phase", "to_phase")),
        Objective(
            name="job-stall-rate",
            description="no job stalls for a sustained window",
            metric="kctpu_job_stalled", threshold=0.5, error_budget=0.2),
        Objective(
            name="failover-time",
            description="p99 gang failover (replacement rendezvous) <= 60s",
            metric="kctpu_restart_latency_seconds", threshold=60.0,
            kind=KIND_HISTOGRAM_QUANTILE, q=0.99, error_budget=0.05,
            subject_labels=()),
        Objective(
            name="sched-queue-wait",
            description="p99 scheduler queue wait <= 300s",
            metric="kctpu_sched_queue_wait_seconds", threshold=300.0,
            kind=KIND_HISTOGRAM_QUANTILE, q=0.99, error_budget=0.05,
            subject_labels=()),
        Objective(
            name="cluster-goodput",
            description="cluster goodput ratio stays >= 0.5",
            metric="kctpu_cluster_goodput_ratio", threshold=0.5,
            direction=DIRECTION_BELOW, error_budget=0.2,
            subject_labels=()),
        Objective(
            name="badput-budget",
            description="per-job goodput ratio stays >= 0.25 (a "
                        "crash-looping or perpetually-compiling job burns "
                        "this without ever failing)",
            metric="kctpu_goodput_ratio", threshold=0.25,
            direction=DIRECTION_BELOW, error_budget=0.2),
    ]


@dataclass
class AlertState:
    """Live evaluation state of (objective, label set)."""

    objective: Objective
    labels: Dict[str, str]
    burn_fast: float = 0.0
    burn_slow: float = 0.0
    value: float = 0.0          # latest evaluated value (quantile/sample)
    active: bool = False
    since: float = 0.0          # when the current active state began
    transitions: int = 0        # fire edges seen (tests assert exactness)

    def series_label(self) -> str:
        if not self.labels:
            return "_cluster"
        return ",".join(f"{k}={v}" for k, v in sorted(self.labels.items()))

    def as_dict(self) -> Dict[str, Any]:
        o = self.objective
        return {
            "slo": o.name, "description": o.description,
            "metric": o.metric, "threshold": o.threshold,
            "labels": dict(self.labels), "value": self.value,
            "burn_fast": round(self.burn_fast, 4),
            "burn_slow": round(self.burn_slow, 4),
            "burn_threshold": o.burn_threshold,
            "active": self.active, "since": self.since,
            "transitions": self.transitions,
        }


#: notifier(state, fired): fired=True on a burn edge, False on recovery.
Notifier = Callable[[AlertState, bool], None]


class SLOEngine:
    def __init__(self, tsdb: TSDB, objectives: Optional[List[Objective]] = None,
                 registry: Optional[Registry] = None,
                 notifier: Optional[Notifier] = None):
        self.tsdb = tsdb
        self.objectives = (default_objectives() if objectives is None
                           else list(objectives))
        self.registry = REGISTRY if registry is None else registry
        self._notifier = notifier
        self._lock = locks.named_lock("obs.slo")
        self._states: Dict[Tuple[str, Tuple[Tuple[str, str], ...]],
                           AlertState] = {}
        self._g_burn = self.registry.gauge(
            "kctpu_slo_burn_rate",
            "Fast-window error-budget burn rate per objective "
            "(1.0 = burning exactly at budget)", ("slo", "series"))
        self._g_active = self.registry.gauge(
            "kctpu_slo_alert_active",
            "1 while an objective's multi-window burn alert is firing",
            ("slo", "series"))

    def set_notifier(self, notifier: Optional[Notifier]) -> None:
        self._notifier = notifier

    def set_objectives(self, objectives: List[Objective]) -> None:
        """Replace the evaluated objective catalogue and drop all alert
        state (smokes compress the windows; operators narrow the set).
        Existing gauge series for dropped states are zeroed, not removed
        — an alert that vanishes mid-flight must read 0, not stale 1."""
        with self._lock:
            for st in self._states.values():
                series = st.series_label()
                self._g_burn.labels(st.objective.name, series).set(0.0)
                self._g_active.labels(st.objective.name, series).set(0.0)
            self._states.clear()
            self.objectives = list(objectives)

    # -- evaluation ----------------------------------------------------------

    def evaluate_once(self, now: Optional[float] = None) -> List[AlertState]:
        """Evaluate every objective over the TSDB; returns the states that
        TRANSITIONED this pass (fired or resolved)."""
        now = time.time() if now is None else now
        edges: List[AlertState] = []
        for obj in self.objectives:
            for labels in self._label_sets(obj):
                st = self._evaluate(obj, labels, now)
                if st is not None:
                    edges.append(st)
        return edges

    def _label_sets(self, obj: Objective) -> List[Dict[str, str]]:
        if obj.kind == KIND_HISTOGRAM_QUANTILE:
            sets = self.tsdb.label_sets(f"{obj.metric}_bucket",
                                        without=("le",))
        else:
            sets = self.tsdb.label_sets(obj.metric)
        return sets or []

    def _evaluate(self, obj: Objective, labels: Dict[str, str],
                  now: float) -> Optional[AlertState]:
        key = (obj.name, tuple(sorted(labels.items())))
        with self._lock:
            st = self._states.get(key)
            if st is None:
                st = self._states[key] = AlertState(objective=obj,
                                                    labels=dict(labels))
        st.burn_fast, value_fast = self._window_burn(
            obj, labels, obj.fast_window_s, now)
        st.burn_slow, _ = self._window_burn(
            obj, labels, obj.slow_window_s, now)
        st.value = value_fast
        series = st.series_label()
        self._g_burn.labels(obj.name, series).set(round(st.burn_fast, 4))
        edge: Optional[bool] = None
        with self._lock:
            if (not st.active and st.burn_fast >= obj.burn_threshold
                    and st.burn_slow >= obj.burn_threshold):
                st.active = True
                st.since = now
                st.transitions += 1
                edge = True
            elif st.active and st.burn_fast < obj.burn_threshold:
                st.active = False
                st.since = now
                edge = False
        self._g_active.labels(obj.name, series).set(1.0 if st.active else 0.0)
        if edge is None:
            return None
        if self._notifier is not None:
            try:
                self._notifier(st, edge)
            except Exception:  # noqa: BLE001 — notification must not kill eval
                pass
        return st

    def _window_burn(self, obj: Objective, labels: Dict[str, str],
                     window_s: float, now: float) -> Tuple[float, float]:
        """(burn, evaluated value) for one window."""
        budget = max(1e-6, obj.error_budget)
        if obj.kind == KIND_HISTOGRAM_QUANTILE:
            value = self.tsdb.quantile_from_histogram(
                obj.metric, labels, obj.q, window_s, now)
            violating = 1.0 if obj.violates(value) else 0.0
            return violating / budget, value
        pts = self.tsdb.points(obj.metric, labels, now - window_s, now)
        if not pts:
            return 0.0, 0.0
        bad = sum(1 for _, v in pts if obj.violates(v))
        return (bad / len(pts)) / budget, pts[-1][1]

    # -- query surface -------------------------------------------------------

    def alerts(self, active_only: bool = True) -> List[Dict[str, Any]]:
        with self._lock:
            states = list(self._states.values())
        out = [s.as_dict() for s in states if s.active or not active_only]
        out.sort(key=lambda d: (not d["active"], d["slo"], d["labels"].items()
                                and sorted(d["labels"].items())))
        return out

    def state(self) -> Dict[str, Any]:
        """The ``GET /debug/slos`` document."""
        return {
            "objectives": [
                {"slo": o.name, "description": o.description,
                 "metric": o.metric, "threshold": o.threshold,
                 "kind": o.kind, "direction": o.direction,
                 "error_budget": o.error_budget,
                 "fast_window_s": o.fast_window_s,
                 "slow_window_s": o.slow_window_s,
                 "burn_threshold": o.burn_threshold}
                for o in self.objectives
            ],
            "alerts": self.alerts(active_only=False),
        }


_DEFAULT: Optional[SLOEngine] = None
_DEFAULT_LOCK = locks.named_lock("obs.slo-default")


def default_slo_engine() -> SLOEngine:
    """Process-global engine over the process-global TSDB (what
    ``/debug/slos`` serves and the controller's obs plane drives)."""
    from .tsdb import default_tsdb

    global _DEFAULT
    with _DEFAULT_LOCK:
        if _DEFAULT is None:
            _DEFAULT = SLOEngine(default_tsdb())
        return _DEFAULT
