"""Flight recorder: postmortem bundles for failed jobs.

When a job reaches a terminal failure (or on demand via ``kctpu debug
dump JOB``) the controller captures everything the obs plane knows about
it into one directory — so the debugging artefacts survive the process
that produced them:

    $KCTPU_DEBUG_DIR/<namespace>-<name>-<ts>/
        manifest.json   what's here + why the bundle was cut
        trace.json      the job's causal trace (Chrome trace_event format,
                        merged across processes, filtered to its trace_id)
        events.json     the recorder's event ring for the job
        progress.json   last progress beats per pod
        status.json     phase-transition history (obs/lifecycle.py ring)
        tsdb.json       relevant retained-series windows (obs/tsdb.py)
        goodput.json    the job's goodput-ledger snapshot (obs/goodput.py)
                        — "where did the time go" without a live TSDB

Everything is passed IN by the caller (controller/controller.py) —
obs/ stays a leaf package with no imports from the control plane.
Bundle writing is best-effort: any OSError is swallowed and reported as
None, because postmortem capture must never make a failing job fail
harder."""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, Optional

from . import trace as trace_mod
from .tsdb import TSDB

# Bundles land under this directory; unset = flight recording disabled.
DEBUG_DIR_ENV = "KCTPU_DEBUG_DIR"

# How much retained history the bundle folds in per series.
DEFAULT_TSDB_WINDOW_S = 600.0


def debug_dir(env: Optional[Dict[str, str]] = None) -> str:
    e = os.environ if env is None else env
    return e.get(DEBUG_DIR_ENV, "")


def collect_trace_events(
        trace_id: str,
        extra: Optional[List[Dict[str, Any]]] = None,
) -> List[Dict[str, Any]]:
    """The job's causal trace: in-process spans plus everything workload
    processes dumped to ``$KCTPU_TRACE_DIR`` (plus any ``extra`` events a
    remote caller fetched, e.g. the API server's span buffer over REST),
    filtered to ``trace_id`` and deduplicated by span id."""
    events: List[Dict[str, Any]] = [
        s.to_event() for s in trace_mod.TRACER.spans()]
    d = os.environ.get(trace_mod.TRACE_DIR_ENV, "")
    if d and os.path.isdir(d):
        events.extend(trace_mod.merge_trace_dir(d))
    if extra:
        events.extend(extra)
    if trace_id:
        events = trace_mod.events_for_trace(events, trace_id)
    seen = set()
    deduped = []
    for e in events:
        _, span_id, _ = trace_mod.event_ids(e)
        key = span_id or id(e)
        if key in seen:
            continue
        seen.add(key)
        deduped.append(e)
    deduped.sort(key=lambda e: e.get("ts", 0))
    return deduped


def record_flight(namespace: str, name: str, *,
                  reason: str = "",
                  trace_id: str = "",
                  events: Optional[List[Dict[str, Any]]] = None,
                  progress: Optional[Dict[str, Any]] = None,
                  status_history: Optional[List[Dict[str, Any]]] = None,
                  status: Optional[Dict[str, Any]] = None,
                  goodput: Optional[Dict[str, Any]] = None,
                  tsdb: Optional[TSDB] = None,
                  tsdb_window_s: float = DEFAULT_TSDB_WINDOW_S,
                  extra_trace_events: Optional[List[Dict[str, Any]]] = None,
                  out_dir: Optional[str] = None,
                  now: Optional[float] = None) -> Optional[str]:
    """Write one postmortem bundle; returns its path, or None when flight
    recording is disabled (no ``$KCTPU_DEBUG_DIR``) or the write failed."""
    base = out_dir if out_dir is not None else debug_dir()
    if not base:
        return None
    t = time.time() if now is None else now
    bundle = os.path.join(base, f"{namespace}-{name}-{int(t)}")
    try:
        os.makedirs(bundle, exist_ok=True)
        trace_events = collect_trace_events(trace_id, extra_trace_events)
        _write_json(bundle, "trace.json", {"traceEvents": trace_events})
        _write_json(bundle, "events.json", events or [])
        _write_json(bundle, "progress.json", progress or {})
        _write_json(bundle, "status.json", {
            "status": status or {},
            "history": status_history or [],
        })
        _write_json(bundle, "tsdb.json",
                    tsdb.dump_window(tsdb_window_s, now=t) if tsdb else {})
        _write_json(bundle, "goodput.json", goodput or {})
        _write_json(bundle, "manifest.json", {
            "namespace": namespace, "name": name, "reason": reason,
            "trace_id": trace_id, "captured_at": t,
            "trace_spans": len(trace_events),
            "events": len(events or []),
            "status_transitions": len(status_history or []),
            "tsdb_window_s": tsdb_window_s,
            "files": ["manifest.json", "trace.json", "events.json",
                      "progress.json", "status.json", "tsdb.json",
                      "goodput.json"],
        })
        return bundle
    except OSError:
        return None


def _write_json(bundle: str, fname: str, obj: Any) -> None:
    with open(os.path.join(bundle, fname), "w", encoding="utf-8") as f:
        json.dump(obj, f, indent=1, sort_keys=True, default=str)


def read_bundle(bundle: str) -> Dict[str, Any]:
    """Load a bundle back as {filename: parsed json} (damaged files skipped)
    — what ``kctpu debug show`` and the completeness tests consume."""
    out: Dict[str, Any] = {}
    try:
        names = sorted(os.listdir(bundle))
    except OSError:
        return out
    for fname in names:
        if not fname.endswith(".json"):
            continue
        try:
            with open(os.path.join(bundle, fname), encoding="utf-8") as f:
                out[fname] = json.load(f)
        except (OSError, ValueError):
            continue
    return out
