"""Goodput ledger: phase-attributed time accounting, queue to step.

The progress plane knows *what* every replica is doing (beats carry a
phase); the control plane knows *whether* it is scheduled, running,
preempted.  This module folds both observation streams into the number a
TPU fleet is actually run on — **goodput**, the fraction of
accelerator-occupied time spent on useful steps — by attributing every
second of each replica's lifetime to exactly one bucket of the closed
taxonomy in :mod:`obs.phases` (``ALL_BUCKETS``).

Design rules:

- **Contiguous by construction.**  Each pod ledger holds one open
  interval (current bucket + since-timestamp); an observation closes it
  at ``now`` and opens the next at the same instant.  Summed buckets
  therefore equal wall-time since first observation exactly — no gaps,
  no double-count at transitions (bench ``--goodput`` still verifies).
- **Leaf purity.**  obs/ imports nothing above it; the controller adapts
  its pods into plain :class:`PodObservation` records.
- **Exact-once across failover.**  The per-job rollup persisted into
  ``TFJobStatus.goodput`` is the ledger's journal checkpoint: a new
  controller seeds :meth:`GoodputTracker.bootstrap` with the carried
  totals from the last written status and accounts forward from its own
  first observation, so a failover coarsens attribution by at most one
  status-publish interval and never double-counts.
- **Series-budget aware.**  One ``kctpu_goodput_ratio`` gauge series and
  up to ``len(ALL_BUCKETS)`` ``kctpu_badput_seconds_total`` counter
  series per job, all removed on job delete.

Attribution at the tricky boundaries (the full table is in
docs/OBSERVABILITY.md):

- compile time accrues as ``compile_miss`` until the beat's
  ``compile_source`` resolves; ``"cache-hit"`` re-attributes the accrued
  episode to ``compile_cached`` (provenance arrives only when the
  compile does).
- the stall detector's verdict overrides the beat bucket: a replica
  beating ``fit`` with a frozen step past deadline is ``stalled``, not
  ``train``.
- ``Failed`` pods with the scheduler's ``Preempted``/``WidthHarvested``
  reason accrue ``preempted``/``harvested`` until the controller
  replaces them — the recovery tail a kill costs; all other terminal
  pods accrue ``terminal`` (excluded from the ratio denominator).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Tuple

from ..utils import locks
from . import metrics as metrics_mod
from .phases import (
    ALL_BUCKETS,
    BUCKET_HARVESTED,
    BUCKET_PREEMPTED,
    BUCKET_QUEUED,
    BUCKET_SCHEDULING,
    BUCKET_STARTING_COLD,
    BUCKET_STARTING_WARM,
    BUCKET_STALLED,
    BUCKET_TERMINAL,
    COMPILE_SOURCE_CACHE_HIT,
    BUCKET_COMPILE_CACHED,
    BUCKET_COMPILE_MISS,
    GOODPUT_BUCKETS,
    NON_OCCUPIED_BUCKETS,
    POD_REASON_HARVESTED_PREFIX,
    POD_REASON_PREEMPTED_PREFIX,
    POD_REASON_QUEUED_PREFIX,
    bucket_for_beat_phase,
)

# Pod phases, restated to keep obs/ a leaf (api/core.py defines the same
# literals; serde stability there is a tier-1 invariant).
_POD_PENDING = "Pending"
_POD_RUNNING = "Running"
_POD_SUCCEEDED = "Succeeded"
_POD_FAILED = "Failed"

# The ratio is meaningless over a cold few seconds (everything is
# rendezvous/compile); gauges publish once a job has this much
# accelerator-occupied time on the books.
RATIO_WARMUP_S = 5.0

# Retired (disappeared) pod ledgers retained per job before the oldest
# are folded into the job's carried totals — bounds memory for a
# crash-looping job that churns replicas forever.
MAX_RETIRED_PODS = 64

# Start-mode annotation the kubelet stamps on pods it admitted from the
# warm pool ("warm") vs cold-booted ("cold"); absent = cold.  Restates
# api.labels.ANNOTATION_START_MODE — obs/ is a leaf package.
ANNOTATION_START_MODE = "kubeflow.caicloud.io/start-mode"
START_MODE_WARM = "warm"
START_MODE_COLD = "cold"


@dataclass
class PodObservation:
    """One pod as the ledger sees it — the controller's adapter output.

    ``beat_phase`` is None when the pod has never beat (starting), else
    the beat's phase string ("" included)."""

    name: str = ""
    pod_phase: str = _POD_PENDING
    reason: str = ""
    start_mode: str = ""              # "" | "cold" | "warm" (annotation)
    beat_phase: Optional[str] = None  # None = no beat yet
    compile_source: str = ""
    stalled: bool = False


@dataclass
class JobGoodputSummary:
    """The per-job rollup: what status/CLI/flight bundles consume."""

    goodput_s: float = 0.0     # time in GOODPUT_BUCKETS
    occupied_s: float = 0.0    # wall minus NON_OCCUPIED_BUCKETS
    wall_s: float = 0.0        # total attributed time
    ratio: float = 0.0         # goodput_s / occupied_s (0 when unoccupied)
    buckets: Dict[str, float] = field(default_factory=dict)  # nonzero only
    replicas: int = 0          # pod ledgers folded in (live + retired)


def bucket_for(obs: PodObservation) -> Optional[str]:
    """The taxonomy decision: one bucket per observation, or None to
    hold the current interval open (indeterminate pod phase)."""
    ph = obs.pod_phase
    if ph == _POD_PENDING:
        if obs.reason.startswith(POD_REASON_QUEUED_PREFIX):
            return BUCKET_QUEUED
        return BUCKET_SCHEDULING
    if ph == _POD_FAILED:
        if obs.reason.startswith(POD_REASON_PREEMPTED_PREFIX):
            return BUCKET_PREEMPTED
        if obs.reason.startswith(POD_REASON_HARVESTED_PREFIX):
            return BUCKET_HARVESTED
        return BUCKET_TERMINAL
    if ph == _POD_SUCCEEDED:
        return BUCKET_TERMINAL
    if ph == _POD_RUNNING:
        if obs.stalled:
            return BUCKET_STALLED
        if obs.beat_phase is None:
            return (BUCKET_STARTING_WARM if obs.start_mode == START_MODE_WARM
                    else BUCKET_STARTING_COLD)
        return bucket_for_beat_phase(obs.beat_phase, obs.compile_source)
    return None  # Unknown: hold the last attribution


class PodLedger:
    """One replica's attributed lifetime: an open interval plus totals.

    Not thread-safe on its own — the owning :class:`GoodputTracker`
    serializes access."""

    __slots__ = ("first_seen", "bucket", "since", "totals",
                 "_unresolved_compile_s", "_compile_resolved", "retired_at")

    def __init__(self, now: float) -> None:
        self.first_seen = now
        self.bucket: Optional[str] = None
        self.since = now
        self.totals: Dict[str, float] = {}
        # compile_miss seconds accrued while provenance was still
        # unreported — moved to compile_cached if it resolves cache-hit.
        self._unresolved_compile_s = 0.0
        # Whether compile provenance was known as of the LAST observation
        # — the open interval accrues under that knowledge, not the next
        # observation's (which is what closes it).
        self._compile_resolved = False
        self.retired_at: Optional[float] = None

    def observe(self, obs: PodObservation, now: float) -> None:
        if self.retired_at is not None:
            return
        now = max(now, self.since)  # clock must not run backward
        nxt = bucket_for(obs)
        self._accrue(now)
        if obs.compile_source and not self._compile_resolved:
            # Provenance just resolved: re-attribute the accrued episode.
            if (obs.compile_source == COMPILE_SOURCE_CACHE_HIT
                    and self._unresolved_compile_s > 0.0):
                moved = min(self._unresolved_compile_s,
                            self.totals.get(BUCKET_COMPILE_MISS, 0.0))
                if moved > 0.0:
                    self.totals[BUCKET_COMPILE_MISS] -= moved
                    self.totals[BUCKET_COMPILE_CACHED] = (
                        self.totals.get(BUCKET_COMPILE_CACHED, 0.0) + moved)
            self._unresolved_compile_s = 0.0
        self._compile_resolved = bool(obs.compile_source)
        if not self._compile_resolved and nxt != BUCKET_COMPILE_MISS:
            # Not compiling and no provenance pending: a later compile
            # episode starts its own unresolved accrual from zero.
            self._unresolved_compile_s = 0.0
        if nxt is not None and nxt != self.bucket:
            self.bucket = nxt

    def retire(self, now: float) -> None:
        """Close the books: the pod disappeared (deleted/replaced)."""
        if self.retired_at is not None:
            return
        now = max(now, self.since)
        self._accrue(now)
        self.retired_at = now
        self.bucket = None

    def _accrue(self, now: float) -> None:
        """Close the open interval at ``now`` into totals, reopening at
        the same instant — the no-gap/no-double-count invariant."""
        if self.bucket is not None:
            dt = now - self.since
            if dt > 0.0:
                self.totals[self.bucket] = (
                    self.totals.get(self.bucket, 0.0) + dt)
                if (self.bucket == BUCKET_COMPILE_MISS
                        and not self._compile_resolved):
                    self._unresolved_compile_s += dt
        self.since = now

    def wall_s(self, now: float) -> float:
        end = self.retired_at if self.retired_at is not None else max(
            now, self.since)
        return end - self.first_seen

    def attributed_s(self, now: float) -> float:
        """Totals plus the open interval — equals :meth:`wall_s` always;
        bench --goodput gates on exactly that."""
        open_s = 0.0
        if self.retired_at is None and self.bucket is not None:
            open_s = max(0.0, now - self.since)
        return sum(self.totals.values()) + open_s

    def snapshot(self, now: float) -> Dict[str, float]:
        """Totals including the open interval, without mutating state."""
        out = dict(self.totals)
        if self.retired_at is None and self.bucket is not None:
            dt = max(0.0, now - self.since)
            if dt > 0.0:
                out[self.bucket] = out.get(self.bucket, 0.0) + dt
        return out


class JobLedger:
    """All of one job's pod ledgers plus carried totals from before this
    controller's first observation (failover bootstrap, retired-pod
    folding)."""

    __slots__ = ("pods", "carried", "retired_order")

    def __init__(self) -> None:
        self.pods: Dict[str, PodLedger] = {}
        self.carried: Dict[str, float] = {}
        self.retired_order: List[str] = []

    def observe(self, observations: Iterable[PodObservation],
                now: float) -> None:
        seen = set()
        for obs in observations:
            if not obs.name:
                continue
            seen.add(obs.name)
            led = self.pods.get(obs.name)
            if led is None:
                led = self.pods[obs.name] = PodLedger(now)
            led.observe(obs, now)
        for name, led in self.pods.items():
            if name not in seen and led.retired_at is None:
                led.retire(now)
                self.retired_order.append(name)
        while len(self.retired_order) > MAX_RETIRED_PODS:
            oldest = self.retired_order.pop(0)
            led = self.pods.pop(oldest, None)
            if led is not None:
                for b, s in led.totals.items():
                    self.carried[b] = self.carried.get(b, 0.0) + s

    def bucket_totals(self, now: float) -> Dict[str, float]:
        out = dict(self.carried)
        for led in self.pods.values():
            for b, s in led.snapshot(now).items():
                out[b] = out.get(b, 0.0) + s
        return out

    def summary(self, now: float) -> JobGoodputSummary:
        totals = self.bucket_totals(now)
        wall = sum(totals.values())
        good = sum(totals.get(b, 0.0) for b in GOODPUT_BUCKETS)
        occupied = wall - sum(
            totals.get(b, 0.0) for b in NON_OCCUPIED_BUCKETS)
        ratio = (good / occupied) if occupied > 0.0 else 0.0
        return JobGoodputSummary(
            goodput_s=good, occupied_s=max(0.0, occupied), wall_s=wall,
            ratio=min(1.0, max(0.0, ratio)),
            buckets={b: s for b, s in sorted(totals.items()) if s > 0.0},
            replicas=len(self.pods))


class GoodputTracker:
    """The controller-facing facade: per-job ledgers keyed ``ns/name``,
    metric publication, cluster rollup.

    Metrics published (catalogued in OBSERVABILITY.md):

    - ``kctpu_goodput_ratio{namespace,tfjob}`` gauge — after warmup;
    - ``kctpu_badput_seconds_total{namespace,tfjob,bucket}`` counter —
      cumulative non-goodput occupied seconds per bucket (monotonic:
      published as increments over the last published value);
    - ``kctpu_cluster_goodput_ratio`` gauge — scrape-time callback over
      every live ledger (``Gauge.set_function``), no per-job fan-out;
    - ``kctpu_tenant_goodput_ratio{tenant}`` gauge — same scrape-time
      aggregation restricted to one tenant's jobs (one series per live
      tenant, registered on first attribution, removed with the
      tenant's last ledger).
    """

    def __init__(self, registry: Optional[metrics_mod.Registry] = None
                 ) -> None:
        reg = registry if registry is not None else metrics_mod.REGISTRY
        self._lock = locks.named_lock("obs.goodput")
        self._jobs: Dict[str, JobLedger] = {}
        # Job key -> tenant, attributed by the controller sync loop (the
        # label-aware tenant; namespace fallback when never attributed).
        self._tenant_by_key: Dict[str, str] = {}
        self._tenants_registered: set = set()
        # Last cumulative badput published per (key, bucket): the delta
        # source for the monotonic counter.
        self._published: Dict[Tuple[str, str], float] = {}
        self._g_ratio = reg.gauge(
            "kctpu_goodput_ratio",
            "Fraction of accelerator-occupied time spent on useful steps",
            ("namespace", "tfjob"))
        self._c_badput = reg.counter(
            "kctpu_badput_seconds_total",
            "Occupied time attributed to non-goodput buckets",
            ("namespace", "tfjob", "bucket"))
        self._g_cluster = reg.gauge(
            "kctpu_cluster_goodput_ratio",
            "Cluster-wide goodput ratio over all live job ledgers")
        self._g_cluster.set_function(self.cluster_ratio)
        self._g_tenant = reg.gauge(
            "kctpu_tenant_goodput_ratio",
            "Per-tenant goodput ratio over the tenant's live job ledgers",
            ("tenant",))

    # -- observation ------------------------------------------------------

    def observe(self, namespace: str, name: str,
                observations: Iterable[PodObservation],
                now: float) -> None:
        """Fold one sync's pod observations into the job's ledger.

        Deliberately returns nothing: the rollup (:meth:`summary`) walks
        every pod ledger, and the sync loop only needs it on the
        quantized status-publish edge — computing it here would put that
        walk on EVERY sync's critical path (the bench --goodput overhead
        gate is exactly this)."""
        key = f"{namespace}/{name}"
        with self._lock:
            job = self._jobs.get(key)
            if job is None:
                job = self._jobs[key] = JobLedger()
            job.observe(observations, now)

    def bootstrap(self, namespace: str, name: str,
                  carried: Dict[str, float]) -> None:
        """Failover seed: adopt the bucket totals the PREVIOUS controller
        persisted into status.goodput, once, before first observation —
        the recompute-from-status journal ride that makes the ledger
        exact-once across failover (coarsened by at most one
        status-publish interval)."""
        key = f"{namespace}/{name}"
        with self._lock:
            if key in self._jobs:
                return  # already observing; the seed would double-count
            job = self._jobs[key] = JobLedger()
            job.carried = {
                b: float(s) for b, s in (carried or {}).items()
                if b in ALL_BUCKETS and float(s) > 0.0}

    def has_job(self, namespace: str, name: str) -> bool:
        with self._lock:
            return f"{namespace}/{name}" in self._jobs

    # -- tenancy -----------------------------------------------------------

    def set_tenant(self, namespace: str, name: str, tenant: str) -> None:
        """Attribute a job's ledger to a tenant (controller sync loop,
        api/tenant.tenant_of).  First attribution of a new tenant
        registers its scrape-time gauge series."""
        key = f"{namespace}/{name}"
        register = False
        with self._lock:
            self._tenant_by_key[key] = tenant
            if tenant not in self._tenants_registered:
                self._tenants_registered.add(tenant)
                register = True
        if register:
            # Instrument call outside our lock (never nest under it).
            self._g_tenant.labels(tenant).set_function(
                lambda t=tenant: self.tenant_ratio(t))

    def _tenant_of_key(self, key: str) -> str:
        t = self._tenant_by_key.get(key)
        if t:
            return t
        return key.split("/", 1)[0] if "/" in key else "default"

    def tenant_ratio(self, tenant: str) -> float:
        """Occupied-time-weighted goodput over one tenant's live ledgers
        (the ``kctpu_tenant_goodput_ratio`` scrape callback); 1.0 under
        warmup, same convention as the cluster rollup."""
        import time as _t
        now = _t.time()
        good = occupied = 0.0
        with self._lock:
            for key, job in self._jobs.items():
                if self._tenant_of_key(key) != tenant:
                    continue
                s = job.summary(now)
                good += s.goodput_s
                occupied += s.occupied_s
        if occupied < RATIO_WARMUP_S:
            return 1.0
        return min(1.0, max(0.0, good / occupied))

    def tenant_rollup(self, now: float) -> Dict[str, Dict[str, float]]:
        """Per-tenant aggregation for ``kctpu goodput --tenant``: jobs,
        goodput/occupied seconds, occupied-weighted ratio."""
        agg: Dict[str, Dict[str, float]] = {}
        with self._lock:
            for key, job in self._jobs.items():
                t = self._tenant_of_key(key)
                s = job.summary(now)
                row = agg.setdefault(
                    t, {"jobs": 0.0, "goodput_s": 0.0, "occupied_s": 0.0})
                row["jobs"] += 1
                row["goodput_s"] += s.goodput_s
                row["occupied_s"] += s.occupied_s
        for row in agg.values():
            o = row["occupied_s"]
            row["ratio"] = (1.0 if o < RATIO_WARMUP_S
                            else min(1.0, max(0.0, row["goodput_s"] / o)))
        return agg

    # -- rollups ----------------------------------------------------------

    def summary(self, namespace: str, name: str,
                now: float) -> Optional[JobGoodputSummary]:
        with self._lock:
            job = self._jobs.get(f"{namespace}/{name}")
            return job.summary(now) if job is not None else None

    def snapshot(self, namespace: str, name: str,
                 now: float) -> Dict[str, object]:
        """Flight-recorder shape: the job rollup plus per-pod books."""
        with self._lock:
            job = self._jobs.get(f"{namespace}/{name}")
            if job is None:
                return {}
            s = job.summary(now)
            return {
                "captured_at": now,
                "goodput_s": round(s.goodput_s, 3),
                "occupied_s": round(s.occupied_s, 3),
                "wall_s": round(s.wall_s, 3),
                "ratio": round(s.ratio, 4),
                "buckets": {b: round(v, 3) for b, v in s.buckets.items()},
                "carried": {b: round(v, 3)
                            for b, v in sorted(job.carried.items())},
                "pods": {
                    pname: {
                        "bucket": led.bucket or "",
                        "retired": led.retired_at is not None,
                        "wall_s": round(led.wall_s(now), 3),
                        "buckets": {b: round(v, 3)
                                    for b, v in sorted(
                                        led.snapshot(now).items())},
                    }
                    for pname, led in sorted(job.pods.items())
                },
            }

    def cluster_ratio(self) -> float:
        """Occupied-time-weighted goodput over every live ledger — the
        ``kctpu_cluster_goodput_ratio`` scrape callback and the
        cluster-goodput SLO's input.  1.0 when nothing is occupied yet
        (an empty cluster is not burning badput)."""
        import time as _t
        now = _t.time()
        good = occupied = 0.0
        with self._lock:
            for job in self._jobs.values():
                s = job.summary(now)
                good += s.goodput_s
                occupied += s.occupied_s
        if occupied < RATIO_WARMUP_S:
            return 1.0
        return min(1.0, max(0.0, good / occupied))

    # -- metric publication ----------------------------------------------

    def publish(self, namespace: str, name: str, now: float) -> None:
        """Push the job's gauge/counter series — called from the sync
        loop after :meth:`observe`.  Counter increments are the delta
        over the last published cumulative value, so the exposition
        stays monotonic whatever the sync cadence."""
        key = f"{namespace}/{name}"
        deltas = []
        with self._lock:
            job = self._jobs.get(key)
            if job is None:
                return
            totals = job.bucket_totals(now)
            for b, cum in totals.items():
                if b in GOODPUT_BUCKETS or b in NON_OCCUPIED_BUCKETS:
                    continue
                last = self._published.get((key, b), 0.0)
                if cum > last:
                    deltas.append((b, cum - last))
                    self._published[(key, b)] = cum
        good = sum(totals.get(b, 0.0) for b in GOODPUT_BUCKETS)
        occupied = sum(totals.values()) - sum(
            totals.get(b, 0.0) for b in NON_OCCUPIED_BUCKETS)
        # Metric writes outside our lock: instrument locks never nest
        # under obs.goodput.
        if occupied >= RATIO_WARMUP_S:
            self._g_ratio.labels(namespace, name).set(
                round(min(1.0, max(0.0, good / occupied)), 4))
        for b, d in deltas:
            self._c_badput.labels(namespace, name, b).inc(d)

    def drop(self, namespace: str, name: str) -> None:
        """Series + state die with the job (delete handler/finalizer)."""
        key = f"{namespace}/{name}"
        dead_tenant = None
        with self._lock:
            self._jobs.pop(key, None)
            tenant = self._tenant_by_key.pop(key, None)
            if (tenant is not None
                    and not any(self._tenant_by_key.get(k) == tenant
                                for k in self._jobs)):
                self._tenants_registered.discard(tenant)
                dead_tenant = tenant
            stale = [k for k in self._published if k[0] == key]
            for k in stale:
                del self._published[k]
        self._g_ratio.remove(namespace, name)
        if dead_tenant is not None:
            self._g_tenant.remove(dead_tenant)
        for b in ALL_BUCKETS:
            self._c_badput.remove(namespace, name, b)
