"""In-process time-series store: metrics *history* for the SLO plane.

``GET /metrics`` is an instantaneous scrape — by the time someone asks
"when did TTFT regress", the evidence is gone.  The TSDB samples the
existing registry (:meth:`~.metrics.Registry.families`) on a cadence and
retains each series as a bounded ring:

- a **raw** ring at the sampling interval for the recent window, and
- a **coarse** ring past the raw horizon (one point per
  ``coarse_step_s``, newest sample in the step wins), so an hour of
  history costs ~120 points per series instead of 3600.

Series count is bounded by the PR-14 budget (``DEFAULT_SERIES_BUDGET``);
overflow drops new series and counts them (``kctpu_tsdb_series_dropped_
total``), exactly the registry's own cardinality-control posture.

Windowed queries (``rate``, ``avg_over_time``, ``quantile_from_
histogram``, ``latest``, ``range``) are served at ``GET /debug/query``
(cluster/apiserver.py) and ``kctpu query``; the SLO engine (obs/slo.py)
evaluates its burn windows against them via :meth:`TSDB.add_listener`.

Everything here is stdlib-only and imports nothing above obs/ —
consumers hand in the registry and drive the clock (``sample_once(now)``
is the testable unit; :meth:`start` merely wraps it in a daemon thread).
"""

from __future__ import annotations

import json
import math
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..utils import locks
from .metrics import DEFAULT_SERIES_BUDGET, REGISTRY, Registry, bucket_quantile

SeriesKey = Tuple[str, Tuple[Tuple[str, str], ...]]


def series_key(name: str, labels: Dict[str, str]) -> SeriesKey:
    return name, tuple(sorted(labels.items()))


class _Series:
    __slots__ = ("name", "labels", "typ", "raw", "coarse")

    def __init__(self, name: str, labels: Dict[str, str], typ: str):
        self.name = name
        self.labels = dict(labels)
        self.typ = typ
        self.raw: deque = deque()      # (ts, value) at the sample cadence
        self.coarse: deque = deque()   # (step_ts, value), newest-in-step

    def points(self, start: float, end: float) -> List[Tuple[float, float]]:
        out = [p for p in self.coarse if start <= p[0] <= end]
        out.extend(p for p in self.raw if start <= p[0] <= end)
        return out


class TSDB:
    """Retained-series sampler over one registry.  Thread-safe; the
    sampling clock is injectable (``sample_once(now=...)``) so retention,
    downsampling and burn-window tests run on synthetic time."""

    def __init__(self, registry: Optional[Registry] = None,
                 interval_s: float = 1.0,
                 retention_s: float = 300.0,
                 coarse_step_s: float = 30.0,
                 coarse_retention_s: float = 3600.0,
                 max_series: int = DEFAULT_SERIES_BUDGET):
        self.registry = REGISTRY if registry is None else registry
        self.interval_s = max(0.05, interval_s)
        self.retention_s = retention_s
        self.coarse_step_s = max(self.interval_s, coarse_step_s)
        self.coarse_retention_s = max(retention_s, coarse_retention_s)
        self.max_series = max_series
        self._lock = locks.named_lock("obs.tsdb")
        self._series: Dict[SeriesKey, _Series] = {}
        self._listeners: List[Callable[[float], None]] = []
        self._thread: Optional[threading.Thread] = None
        self._stop: Optional[threading.Event] = None
        # Self-telemetry on the sampled registry (the catalogue rows the
        # metric-catalogue vet rule checks).
        self._g_series = self.registry.gauge(
            "kctpu_tsdb_series", "Series currently retained by the TSDB")
        self._c_samples = self.registry.counter(
            "kctpu_tsdb_samples_total", "Points appended by the TSDB sampler")
        self._c_dropped = self.registry.counter(
            "kctpu_tsdb_series_dropped_total",
            "New series dropped because the TSDB hit its series budget")

    # -- sampling ------------------------------------------------------------

    def sample_once(self, now: Optional[float] = None) -> int:
        """One sampling pass over the registry; returns points appended."""
        now = time.time() if now is None else now
        appended = 0
        dropped = 0
        for fam in self.registry.families():
            for s in fam.samples:
                key = series_key(fam.name + s.suffix, s.labels)
                with self._lock:
                    series = self._series.get(key)
                    if series is None:
                        if len(self._series) >= self.max_series:
                            dropped += 1
                            continue
                        series = self._series[key] = _Series(
                            key[0], s.labels, fam.typ)
                    self._append_locked(series, now, s.value)
                appended += 1
        if appended:
            self._c_samples.inc(appended)
        if dropped:
            self._c_dropped.inc(dropped)
        with self._lock:
            self._g_series.set(float(len(self._series)))
            listeners = list(self._listeners)
        for fn in listeners:
            try:
                fn(now)
            except Exception:  # noqa: BLE001 — a listener never kills sampling
                pass
        return appended

    def _append_locked(self, series: _Series, now: float, value: float) -> None:
        series.raw.append((now, value))
        horizon = now - self.retention_s
        while series.raw and series.raw[0][0] < horizon:
            ts, v = series.raw.popleft()
            # Downsample past the raw horizon: one point per coarse step,
            # the newest sample in the step winning (right for monotonic
            # counters; a defensible "last observation" for gauges).
            step = ts - (ts % self.coarse_step_s)
            if series.coarse and series.coarse[-1][0] == step:
                series.coarse[-1] = (step, v)
            else:
                series.coarse.append((step, v))
        coarse_horizon = now - self.coarse_retention_s
        while series.coarse and series.coarse[0][0] < coarse_horizon:
            series.coarse.popleft()

    def add_listener(self, fn: Callable[[float], None]) -> None:
        """Call ``fn(sample_time)`` after every sampling pass (the SLO
        engine's evaluation hook)."""
        with self._lock:
            if fn not in self._listeners:
                self._listeners.append(fn)

    def start(self) -> None:
        """Background sampling at ``interval_s`` (idempotent)."""
        with self._lock:
            if self._thread is not None:
                return
            self._stop = threading.Event()
            self._thread = threading.Thread(
                target=self._loop, name="tsdb-sampler", daemon=True)
            self._thread.start()

    def stop(self) -> None:
        with self._lock:
            thread, self._thread = self._thread, None
            stop, self._stop = self._stop, None
        if stop is not None:
            stop.set()
        if thread is not None:
            thread.join(timeout=2.0)

    @property
    def running(self) -> bool:
        with self._lock:
            return self._thread is not None

    def _loop(self) -> None:
        stop = self._stop
        while stop is not None and not stop.wait(self.interval_s):
            try:
                self.sample_once()
            except Exception:  # noqa: BLE001 — sampling must never die
                pass

    # -- queries -------------------------------------------------------------

    def series_count(self) -> int:
        with self._lock:
            return len(self._series)

    def series_names(self, prefix: str = "") -> List[str]:
        with self._lock:
            names = {s.name for s in self._series.values()}
        return sorted(n for n in names if n.startswith(prefix))

    def _get(self, name: str, labels: Dict[str, str]) -> Optional[_Series]:
        with self._lock:
            return self._series.get(series_key(name, labels))

    def points(self, name: str, labels: Dict[str, str],
               start: float, end: float) -> List[Tuple[float, float]]:
        s = self._get(name, labels)
        if s is None:
            return []
        with self._lock:
            return s.points(start, end)

    def latest(self, name: str,
               labels: Dict[str, str]) -> Optional[Tuple[float, float]]:
        s = self._get(name, labels)
        if s is None:
            return None
        with self._lock:
            if s.raw:
                return s.raw[-1]
            return s.coarse[-1] if s.coarse else None

    def rate(self, name: str, labels: Dict[str, str], window_s: float,
             now: Optional[float] = None) -> float:
        """Per-second increase of a counter over the window (0.0 when
        fewer than two points; counter resets clamp to 0)."""
        now = time.time() if now is None else now
        pts = self.points(name, labels, now - window_s, now)
        if len(pts) < 2:
            return 0.0
        (t0, v0), (t1, v1) = pts[0], pts[-1]
        if t1 <= t0:
            return 0.0
        return max(0.0, v1 - v0) / (t1 - t0)

    def avg_over_time(self, name: str, labels: Dict[str, str],
                      window_s: float, now: Optional[float] = None) -> float:
        now = time.time() if now is None else now
        pts = self.points(name, labels, now - window_s, now)
        if not pts:
            return 0.0
        return sum(v for _, v in pts) / len(pts)

    def label_sets(self, name: str,
                   without: Tuple[str, ...] = ()) -> List[Dict[str, str]]:
        """Distinct label sets stored for ``name`` (minus ``without`` keys)
        — how per-job SLO objectives enumerate their series."""
        with self._lock:
            series = [s for s in self._series.values() if s.name == name]
        out: List[Dict[str, str]] = []
        seen = set()
        for s in series:
            ls = {k: v for k, v in s.labels.items() if k not in without}
            key = tuple(sorted(ls.items()))
            if key not in seen:
                seen.add(key)
                out.append(ls)
        return out

    def quantile_from_histogram(self, name: str, labels: Dict[str, str],
                                q: float, window_s: Optional[float] = None,
                                now: Optional[float] = None) -> float:
        """Quantile estimate from a histogram family's retained ``_bucket``
        series: windowed (bucket increase over ``window_s``) when a window
        is given, else over the histogram's whole lifetime (latest
        cumulative counts).  ``labels`` are the family's labels without
        ``le``."""
        now = time.time() if now is None else now
        with self._lock:
            buckets = [
                s for s in self._series.values()
                if s.name == f"{name}_bucket"
                and {k: v for k, v in s.labels.items() if k != "le"} == labels
            ]
        per_le: List[Tuple[float, float]] = []  # (upper, cumulative count)
        for s in buckets:
            le = s.labels.get("le", "")
            upper = math.inf if le == "+Inf" else _parse_float(le)
            if upper is None:
                continue
            with self._lock:
                if window_s is None:
                    pts = s.points(now - self.coarse_retention_s, now)
                    cum = pts[-1][1] if pts else 0.0
                else:
                    pts = s.points(now - window_s, now)
                    cum = (pts[-1][1] - pts[0][1]) if len(pts) >= 2 else (
                        pts[-1][1] if pts else 0.0)
            per_le.append((upper, max(0.0, cum)))
        if not per_le:
            return 0.0
        per_le.sort(key=lambda t: t[0])
        uppers = [u for u, _ in per_le if not math.isinf(u)]
        if not uppers:
            return 0.0
        # Cumulative -> per-bucket, with the +Inf overflow as the last slot
        # (bucket_quantile's contract: len(uppers) + 1 counts).
        cums = [c for _, c in per_le]
        total = cums[-1] if math.isinf(per_le[-1][0]) else cums[-1]
        noncum: List[float] = []
        prev = 0.0
        for u, c in per_le:
            if math.isinf(u):
                continue
            noncum.append(max(0.0, c - prev))
            prev = c
        overflow = max(0.0, total - prev)
        return bucket_quantile(uppers, noncum + [overflow], q)

    # -- the /debug/query surface -------------------------------------------

    def query(self, params: Dict[str, str]) -> Dict[str, Any]:
        """Evaluate one query described by string params (the HTTP query
        string of ``GET /debug/query`` and the flags of ``kctpu query``):

        ``op``      latest | range | rate | avg_over_time | quantile
        ``name``    series (or histogram family, for ``quantile``) name
        ``labels``  JSON object of label matchers (default ``{}``)
        ``window``  window seconds (rate/avg/quantile; range span)
        ``q``       quantile in [0,1] (``quantile`` only)

        Unknown ops or damaged params return ``{"error": ...}`` rather
        than raising — this is a debug surface, never a crash vector."""
        op = params.get("op", "latest")
        name = params.get("name", "")
        if not name and op != "series":
            return {"error": "missing ?name="}
        try:
            labels = json.loads(params.get("labels", "") or "{}")
            if not isinstance(labels, dict):
                raise ValueError("labels must be a JSON object")
            labels = {str(k): str(v) for k, v in labels.items()}
        except ValueError as e:
            return {"error": f"bad labels: {e}"}
        window = _parse_float(params.get("window", "")) or 60.0
        now = time.time()
        base = {"op": op, "name": name, "labels": labels, "window": window}
        if op == "series":
            return {"op": "series",
                    "series": self.series_names(params.get("name", ""))}
        if op == "latest":
            pt = self.latest(name, labels)
            return {**base, "point": list(pt) if pt else None}
        if op == "range":
            pts = self.points(name, labels, now - window, now)
            return {**base, "points": [list(p) for p in pts]}
        if op == "rate":
            return {**base, "value": self.rate(name, labels, window, now)}
        if op == "avg_over_time":
            return {**base,
                    "value": self.avg_over_time(name, labels, window, now)}
        if op == "quantile":
            q = _parse_float(params.get("q", "")) or 0.99
            return {**base, "q": q,
                    "value": self.quantile_from_histogram(
                        name, labels, q, window, now)}
        return {"error": f"unknown op {op!r}"}

    def dump_window(self, window_s: float, prefix: str = "kctpu_",
                    now: Optional[float] = None) -> Dict[str, Any]:
        """Recent points for every retained series under ``prefix`` — the
        flight recorder's metrics-history section."""
        now = time.time() if now is None else now
        with self._lock:
            series = [s for s in self._series.values()
                      if s.name.startswith(prefix)]
            out = []
            for s in series:
                pts = s.points(now - window_s, now)
                if pts:
                    out.append({"name": s.name, "labels": s.labels,
                                "points": [list(p) for p in pts]})
        return {"window_s": window_s, "end": now, "series": out}


def _parse_float(text: str) -> Optional[float]:
    try:
        return float(text)
    except (TypeError, ValueError):
        return None


_DEFAULT: Optional[TSDB] = None
_DEFAULT_LOCK = locks.named_lock("obs.tsdb-default")


def default_tsdb() -> TSDB:
    """The process-global TSDB over the process-global registry — what the
    API server's ``/debug/query`` route and the controller's obs plane
    share (the REGISTRY/TRACER singleton pattern)."""
    global _DEFAULT
    with _DEFAULT_LOCK:
        if _DEFAULT is None:
            _DEFAULT = TSDB()
        return _DEFAULT
