"""Cross-cutting observability: span tracing + Prometheus-style metrics.

The reference controller's only observability channels are glog lines, k8s
Events, and ``TFJob.Status`` (SURVEY.md §5).  This package is the
measurement substrate the ROADMAP's perf work reports against:

- :mod:`.trace` — a lightweight thread-safe span tracer (ring-buffered,
  queryable by tests, dumpable as Chrome ``trace_event`` JSON) wired
  through the reconcile loop and the workload launch path;
- :mod:`.metrics` — counters/gauges/histograms plus a registry that
  renders everything in Prometheus text exposition format (served as
  ``GET /metrics`` by the in-process API server);
- :mod:`.lifecycle` — per-job phase-transition histograms
  (Pending→Running→Succeeded), fed by the status updater;
- :mod:`.tsdb` — in-process retained-series store sampling the registry
  on a cadence, with windowed queries (rate/avg/quantile) behind
  ``GET /debug/query`` and ``kctpu query``;
- :mod:`.slo` — declarative objectives evaluated over TSDB windows with
  multi-window burn-rate alerting (``kctpu alerts``);
- :mod:`.flight` — postmortem bundles (trace + events + progress +
  status history + TSDB windows + goodput ledger) cut on terminal job
  failure;
- :mod:`.phases` — the shared phase/bucket vocabulary (beat phases,
  stall-hold set, ledger taxonomy, pod-reason prefixes);
- :mod:`.goodput` — the goodput ledger: per-job phase-attributed time
  accounting from queue to step (``kctpu goodput``).

Everything is stdlib-only and safe to import from any layer (no imports
back into controller/cluster/workloads).
"""

from .metrics import (  # noqa: F401
    Counter,
    Gauge,
    Histogram,
    Registry,
    REGISTRY,
    validate_exposition,
)
from .trace import (  # noqa: F401
    Span,
    TraceContext,
    Tracer,
    TRACER,
    TRACE_CONTEXT_ENV,
    TRACE_DIR_ENV,
    TRACE_SAMPLE_ENV,
    causal_tree,
    context,
    context_from_env,
    dump_to_env_dir,
    load_trace_events,
    merge_trace_dir,
    orphan_events,
    render_timeline,
    span,
)
from .lifecycle import JobLifecycle, job_lifecycle  # noqa: F401
from .tsdb import TSDB, default_tsdb  # noqa: F401
from .slo import Objective, SLOEngine, default_objectives, default_slo_engine  # noqa: F401
from .flight import DEBUG_DIR_ENV, read_bundle, record_flight  # noqa: F401
from .phases import (  # noqa: F401
    ALL_BUCKETS,
    GOODPUT_BUCKETS,
    KNOWN_PHASES,
    STALL_HOLD_PHASES,
    bucket_for_beat_phase,
)
from .goodput import (  # noqa: F401
    GoodputTracker,
    JobGoodputSummary,
    PodObservation,
)
