"""Cross-cutting observability: span tracing + Prometheus-style metrics.

The reference controller's only observability channels are glog lines, k8s
Events, and ``TFJob.Status`` (SURVEY.md §5).  This package is the
measurement substrate the ROADMAP's perf work reports against:

- :mod:`.trace` — a lightweight thread-safe span tracer (ring-buffered,
  queryable by tests, dumpable as Chrome ``trace_event`` JSON) wired
  through the reconcile loop and the workload launch path;
- :mod:`.metrics` — counters/gauges/histograms plus a registry that
  renders everything in Prometheus text exposition format (served as
  ``GET /metrics`` by the in-process API server);
- :mod:`.lifecycle` — per-job phase-transition histograms
  (Pending→Running→Succeeded), fed by the status updater.

Everything is stdlib-only and safe to import from any layer (no imports
back into controller/cluster/workloads).
"""

from .metrics import (  # noqa: F401
    Counter,
    Gauge,
    Histogram,
    Registry,
    REGISTRY,
    validate_exposition,
)
from .trace import (  # noqa: F401
    Span,
    Tracer,
    TRACER,
    TRACE_DIR_ENV,
    dump_to_env_dir,
    load_trace_events,
    merge_trace_dir,
    span,
)
from .lifecycle import JobLifecycle, job_lifecycle  # noqa: F401
