"""Lightweight span tracer with Chrome ``trace_event`` export.

Answers the question round 5 spent a whole cycle bisecting by hand
(BASELINE.md's ~1s rendezvous stall): *where* does a slow reconcile or a
bimodal job start spend its time?  Spans are recorded into a thread-safe
ring buffer (old spans fall off; tracing never grows unbounded), are
queryable by tests (:meth:`Tracer.spans`), and dump as Chrome
``chrome://tracing`` / Perfetto-loadable JSON.

Cross-process collection: workload processes (pods) dump their spans to
``$KCTPU_TRACE_DIR/trace-<pid>-<nonce>.json`` — explicitly via
:func:`dump_to_env_dir` at the end of a workload's ``main`` (the warm-pool
zygote exits children through ``os._exit``, which skips ``atexit``), with
an ``atexit`` fallback for plainly-spawned processes.  ``bench.py`` and
``kctpu run --trace-out`` merge those files with the controller process's
own spans into one timeline (wall-clock timestamps align processes).
"""

from __future__ import annotations

import atexit
import json
import os
import threading
import time
import uuid
from collections import deque
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, List, Optional

from ..utils import locks

TRACE_DIR_ENV = "KCTPU_TRACE_DIR"


@dataclass
class Span:
    """One completed (or in-flight, inside ``with``) span."""

    name: str
    ts: float = 0.0            # wall-clock start, seconds since epoch
    dur: float = 0.0           # seconds (perf_counter delta)
    pid: int = 0
    tid: int = 0
    parent: str = ""           # enclosing span's name ("" at top level)
    args: Dict[str, Any] = field(default_factory=dict)

    def to_event(self) -> Dict[str, Any]:
        """Chrome trace_event "complete" (ph=X) event, microseconds."""
        ev = {
            "name": self.name,
            "ph": "X",
            "ts": self.ts * 1e6,
            "dur": self.dur * 1e6,
            "pid": self.pid,
            "tid": self.tid,
            "cat": self.name.split("/", 1)[0],
        }
        args = dict(self.args)
        if self.parent:
            args["parent"] = self.parent
        if args:
            ev["args"] = args
        return ev


class Tracer:
    def __init__(self, capacity: int = 8192):
        self._lock = locks.named_lock("obs.tracer")
        self._spans: deque = deque(maxlen=capacity)
        self._local = threading.local()

    @property
    def capacity(self) -> int:
        return self._spans.maxlen

    def _stack(self) -> List[str]:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    @contextmanager
    def span(self, name: str, **args) -> Iterator[Span]:
        """Record a span around the ``with`` body.  Yields the Span object;
        its ``dur`` is final after the block exits, and extra attributes can
        be added to ``span.args`` from inside the block."""
        stack = self._stack()
        sp = Span(name=name, ts=time.time(), pid=os.getpid(),
                  tid=threading.get_ident(),
                  parent=stack[-1] if stack else "", args=args)
        stack.append(name)
        t0 = time.perf_counter()
        try:
            yield sp
        finally:
            sp.dur = time.perf_counter() - t0
            stack.pop()
            with self._lock:
                self._spans.append(sp)

    # -- queries -------------------------------------------------------------

    def spans(self, prefix: Optional[str] = None) -> List[Span]:
        with self._lock:
            out = list(self._spans)
        if prefix is not None:
            out = [s for s in out if s.name.startswith(prefix)]
        return out

    def __len__(self) -> int:
        with self._lock:
            return len(self._spans)

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()

    # -- export --------------------------------------------------------------

    def chrome_trace(self) -> Dict[str, Any]:
        return {
            "traceEvents": [s.to_event() for s in self.spans()],
            "displayTimeUnit": "ms",
        }

    def dump(self, path: str) -> None:
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as fh:
            json.dump(self.chrome_trace(), fh)
        os.replace(tmp, path)


#: Process-global default tracer.
TRACER = Tracer()


@contextmanager
def span(name: str, **args) -> Iterator[Span]:
    """``with obs.span("sync/gather", key=key): ...`` on the global tracer."""
    with TRACER.span(name, **args) as sp:
        yield sp


# ---------------------------------------------------------------------------
# Cross-process dump/merge
# ---------------------------------------------------------------------------

def dump_to_env_dir(tracer: Optional[Tracer] = None) -> Optional[str]:
    """Dump the tracer to ``$KCTPU_TRACE_DIR`` (unique file per process);
    no-op (returns None) when the env var is unset or nothing was traced."""
    # `is None`, not `or`: an empty Tracer is falsy (len 0) but still the
    # caller's tracer — `or` would silently dump the global one instead.
    t = TRACER if tracer is None else tracer
    d = os.environ.get(TRACE_DIR_ENV, "")
    if not d or len(t) == 0:
        return None
    try:
        os.makedirs(d, exist_ok=True)
        path = os.path.join(d, f"trace-{os.getpid()}-{uuid.uuid4().hex[:8]}.json")
        t.dump(path)
        return path
    except OSError:
        return None  # tracing must never fail the workload


def load_trace_events(path: str) -> List[Dict[str, Any]]:
    """Read one Chrome trace JSON file's event list ([] on any damage)."""
    try:
        with open(path) as fh:
            doc = json.load(fh)
    except (OSError, ValueError):
        return []
    if isinstance(doc, list):  # bare-array Chrome trace flavor
        return [e for e in doc if isinstance(e, dict)]
    evs = doc.get("traceEvents") if isinstance(doc, dict) else None
    return [e for e in evs if isinstance(e, dict)] if isinstance(evs, list) else []


def merge_trace_dir(trace_dir: str,
                    tracer: Optional[Tracer] = None) -> Dict[str, Any]:
    """One Chrome trace document from every per-process dump in
    ``trace_dir`` plus (optionally) a live tracer's spans."""
    events: List[Dict[str, Any]] = []
    if trace_dir and os.path.isdir(trace_dir):
        for name in sorted(os.listdir(trace_dir)):
            if name.startswith("trace-") and name.endswith(".json"):
                events.extend(load_trace_events(os.path.join(trace_dir, name)))
    if tracer is not None:
        events.extend(s.to_event() for s in tracer.spans())
    events.sort(key=lambda e: e.get("ts", 0))
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def _atexit_dump() -> None:  # pragma: no cover - exercised in subprocesses
    try:
        dump_to_env_dir()
    except Exception:
        pass


atexit.register(_atexit_dump)
