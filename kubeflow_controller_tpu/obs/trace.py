"""Causal span tracer with Chrome ``trace_event`` export.

Answers the question round 5 spent a whole cycle bisecting by hand
(BASELINE.md's ~1s rendezvous stall): *where* does a slow reconcile or a
bimodal job start spend its time?  Spans are recorded into a thread-safe
ring buffer (old spans fall off; tracing never grows unbounded), are
queryable by tests (:meth:`Tracer.spans`), and dump as Chrome
``chrome://tracing`` / Perfetto-loadable JSON.

**Causal context (PR 16).**  Every span carries ``trace_id`` /
``span_id`` / ``parent_id``.  Parenting is id-based (a thread-local stack
of live Span objects), never name-based — two concurrent same-named spans
on different threads can no longer adopt each other's children.  A
:class:`TraceContext` crosses process boundaries as one string
(``trace:span:flags``) carried on TFJob/Pod annotations and injected into
workload env as ``$KCTPU_TRACE_CONTEXT``; a span recorded with no
enclosing local span parents to the propagated context, so the merged
timeline of controller, scheduler, kubelet and workload processes is one
connected causal tree per job.  The trace id is *derived
deterministically from the job uid* (:meth:`TraceContext.for_job`), so
processes that never exchanged the context string still agree on it.

**Sampling** is head-based per trace id (``$KCTPU_TRACE_SAMPLE``,
default 1.0): the keep/drop decision is a pure function of the trace id,
so every process makes the same call and a kept trace is complete.
Context-less spans (the controller's own sync spans) are always kept —
sampling exists to bound the per-job span volume at ``--scale 10000``.

Cross-process collection: workload processes (pods) dump their spans to
``$KCTPU_TRACE_DIR/trace-<pid>-<nonce>.json`` — explicitly via
:func:`dump_to_env_dir` at the end of a workload's ``main`` (the warm-pool
zygote exits children through ``os._exit``, which skips ``atexit``), with
an ``atexit`` fallback for plainly-spawned processes.  ``bench.py`` and
``kctpu run --trace-out`` merge those files with the controller process's
own spans into one timeline (wall-clock timestamps align processes).
"""

from __future__ import annotations

import atexit
import hashlib
import json
import os
import threading
import time
import uuid
from collections import deque
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, List, Optional, Tuple

from ..utils import locks

TRACE_DIR_ENV = "KCTPU_TRACE_DIR"
#: Cross-process causal context (``TraceContext.encode()`` string),
#: stamped on pods by the planner and injected by the kubelet.
TRACE_CONTEXT_ENV = "KCTPU_TRACE_CONTEXT"
#: Head-based sampling rate in [0, 1]; default 1.0 (keep everything).
TRACE_SAMPLE_ENV = "KCTPU_TRACE_SAMPLE"


def _hash16(text: str) -> str:
    return hashlib.sha1(text.encode()).hexdigest()[:16]


def new_span_id() -> str:
    return uuid.uuid4().hex[:16]


def sample_rate(env: Optional[Dict[str, str]] = None) -> float:
    """The configured head-sampling rate, clamped to [0, 1]."""
    e = os.environ if env is None else env
    try:
        rate = float(e.get(TRACE_SAMPLE_ENV, "") or 1.0)
    except ValueError:
        return 1.0
    return min(1.0, max(0.0, rate))


def trace_sampled(trace_id: str, rate: Optional[float] = None) -> bool:
    """Deterministic head-based keep/drop for a trace id: a pure function
    of the id, so every process (controller, kubelet, workload) makes the
    SAME decision and a sampled trace is never partial."""
    if rate is None:
        rate = sample_rate()
    if rate >= 1.0:
        return True
    if rate <= 0.0:
        return False
    try:
        bucket = int(trace_id[:8] or "0", 16) % 1000000
    except ValueError:
        bucket = 0
    return bucket < rate * 1000000


@dataclass(frozen=True)
class TraceContext:
    """The portable half of a causal trace: which trace, and which span
    new work should parent to.  Encodes as ``trace_id:span_id:flags``."""

    trace_id: str
    span_id: str = ""
    sampled: bool = True

    def encode(self) -> str:
        return f"{self.trace_id}:{self.span_id}:{'01' if self.sampled else '00'}"

    @staticmethod
    def decode(value: str) -> Optional["TraceContext"]:
        """Parse an encoded context (None on any damage — a torn
        annotation must never break a sync)."""
        if not value:
            return None
        parts = value.strip().split(":")
        if len(parts) < 2 or not parts[0]:
            return None
        sampled = parts[2] != "00" if len(parts) > 2 else True
        return TraceContext(trace_id=parts[0], span_id=parts[1],
                            sampled=sampled)

    @staticmethod
    def for_job(uid: str, rate: Optional[float] = None) -> "TraceContext":
        """The job's canonical context, derived deterministically from its
        uid: trace id, root span id, and the head-sampling decision.  Any
        process holding the uid reconstructs the identical context."""
        trace_id = _hash16(f"kctpu-trace:{uid}")
        return TraceContext(
            trace_id=trace_id,
            span_id=_hash16(f"kctpu-root:{uid}"),
            sampled=trace_sampled(trace_id, rate),
        )

    def child(self, span_id: str) -> "TraceContext":
        """The context a downstream hop should parent under."""
        return TraceContext(self.trace_id, span_id, self.sampled)


def context_from_env(env: Optional[Dict[str, str]] = None) -> Optional[TraceContext]:
    e = os.environ if env is None else env
    return TraceContext.decode(e.get(TRACE_CONTEXT_ENV, ""))


_PROCESS_CTX: Optional[TraceContext] = None
_PROCESS_CTX_LOADED = False
_PROCESS_CTX_LOCK = locks.named_lock("obs.trace-process-ctx")


def process_context() -> Optional[TraceContext]:
    """The context this whole PROCESS runs under (``$KCTPU_TRACE_CONTEXT``,
    injected by the kubelet for pod processes), parsed once.  Workload
    spans with no enclosing span attach here automatically."""
    global _PROCESS_CTX, _PROCESS_CTX_LOADED
    if not _PROCESS_CTX_LOADED:
        with _PROCESS_CTX_LOCK:
            if not _PROCESS_CTX_LOADED:
                _PROCESS_CTX = context_from_env()
                _PROCESS_CTX_LOADED = True
    return _PROCESS_CTX


@dataclass
class Span:
    """One completed (or in-flight, inside ``with``) span."""

    name: str
    ts: float = 0.0            # wall-clock start, seconds since epoch
    dur: float = 0.0           # seconds (perf_counter delta)
    pid: int = 0
    tid: int = 0
    parent: str = ""           # enclosing span's NAME (display only)
    args: Dict[str, Any] = field(default_factory=dict)
    trace_id: str = ""         # causal identity ("" = context-less span)
    span_id: str = ""
    parent_id: str = ""        # causal parent (id-based, unambiguous)

    def to_event(self) -> Dict[str, Any]:
        """Chrome trace_event "complete" (ph=X) event, microseconds.

        The pre-PR16 shape (name/ph/ts/dur/pid/tid/cat + ``args.parent``
        as the enclosing NAME) is preserved byte-for-byte; the causal ids
        ride as extra args keys."""
        ev = {
            "name": self.name,
            "ph": "X",
            "ts": self.ts * 1e6,
            "dur": self.dur * 1e6,
            "pid": self.pid,
            "tid": self.tid,
            "cat": self.name.split("/", 1)[0],
        }
        args = dict(self.args)
        if self.parent:
            args["parent"] = self.parent
        if self.trace_id:
            args["trace_id"] = self.trace_id
        if self.span_id:
            args["span_id"] = self.span_id
        if self.parent_id:
            args["parent_id"] = self.parent_id
        if args:
            ev["args"] = args
        return ev


class Tracer:
    def __init__(self, capacity: int = 8192):
        self._lock = locks.named_lock("obs.tracer")
        self._spans: deque = deque(maxlen=capacity)
        self._local = threading.local()

    @property
    def capacity(self) -> int:
        return self._spans.maxlen

    def _stack(self) -> List[Span]:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    # -- causal context ------------------------------------------------------

    def current_context(self) -> Optional[TraceContext]:
        """The active context: a thread-local one (``with tracer.context``)
        wins over the process-level env context."""
        ctx = getattr(self._local, "ctx", None)
        return ctx if ctx is not None else process_context()

    @contextmanager
    def context(self, ctx: Optional[TraceContext]) -> Iterator[Optional[TraceContext]]:
        """Attach spans recorded in this block (this thread) to ``ctx``.
        ``None`` is a no-op passthrough so call sites need no branching."""
        prev = getattr(self._local, "ctx", None)
        self._local.ctx = ctx if ctx is not None else prev
        try:
            yield ctx
        finally:
            self._local.ctx = prev

    @contextmanager
    def span(self, name: str, **args) -> Iterator[Span]:
        """Record a span around the ``with`` body.  Yields the Span object;
        its ``dur`` is final after the block exits, and extra attributes can
        be added to ``span.args`` from inside the block."""
        stack = self._stack()
        ctx = self.current_context()
        sp = Span(name=name, ts=time.time(), pid=os.getpid(),
                  tid=threading.get_ident(),
                  parent=stack[-1].name if stack else "", args=args,
                  span_id=new_span_id())
        if ctx is not None:
            sp.trace_id = ctx.trace_id
            # Parent to the nearest enclosing span of the SAME trace; a
            # context activated under unrelated (context-less) outer spans
            # parents to the propagated remote span instead — that is the
            # cross-process edge.
            for enclosing in reversed(stack):
                if enclosing.trace_id == ctx.trace_id:
                    sp.parent_id = enclosing.span_id
                    break
            else:
                sp.parent_id = ctx.span_id
        elif stack:
            sp.parent_id = stack[-1].span_id
            sp.trace_id = stack[-1].trace_id
        stack.append(sp)
        t0 = time.perf_counter()
        try:
            yield sp
        finally:
            sp.dur = time.perf_counter() - t0
            stack.pop()
            # Head-based sampling drops only CONTEXT spans; the tracer's
            # own context-less spans always record (tests and `kctpu
            # trace` rely on them).
            if ctx is None or ctx.sampled:
                with self._lock:
                    self._spans.append(sp)

    def add_span(self, name: str, ts: float, dur: float, *,
                 ctx: Optional[TraceContext] = None, parent_id: str = "",
                 span_id: str = "", **args) -> Optional[Span]:
        """Record an already-timed span (synthetic timestamps): the shape
        queue-wait and other measured-after-the-fact intervals take.
        Returns None (recording nothing) for an unsampled context."""
        if ctx is not None and not ctx.sampled:
            return None
        sp = Span(name=name, ts=ts, dur=max(0.0, dur), pid=os.getpid(),
                  tid=threading.get_ident(), args=args,
                  span_id=span_id or new_span_id(), parent_id=parent_id)
        if ctx is not None:
            sp.trace_id = ctx.trace_id
            # Default the causal edge to the context's root — unless this
            # IS the root span (span_id == ctx.span_id), which must stay
            # parentless or the tree walk would loop on a self-edge.
            if not parent_id and sp.span_id != ctx.span_id:
                sp.parent_id = ctx.span_id
        with self._lock:
            self._spans.append(sp)
        return sp

    # -- queries -------------------------------------------------------------

    def spans(self, prefix: Optional[str] = None) -> List[Span]:
        with self._lock:
            out = list(self._spans)
        if prefix is not None:
            out = [s for s in out if s.name.startswith(prefix)]
        return out

    def __len__(self) -> int:
        with self._lock:
            return len(self._spans)

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()

    # -- export --------------------------------------------------------------

    def chrome_trace(self) -> Dict[str, Any]:
        return {
            "traceEvents": [s.to_event() for s in self.spans()],
            "displayTimeUnit": "ms",
        }

    def dump(self, path: str) -> None:
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as fh:
            json.dump(self.chrome_trace(), fh)
        os.replace(tmp, path)


#: Process-global default tracer.
TRACER = Tracer()


@contextmanager
def span(name: str, **args) -> Iterator[Span]:
    """``with obs.span("sync/gather", key=key): ...`` on the global tracer."""
    with TRACER.span(name, **args) as sp:
        yield sp


@contextmanager
def context(ctx: Optional[TraceContext]) -> Iterator[Optional[TraceContext]]:
    """``with trace.context(ctx): ...`` on the global tracer."""
    with TRACER.context(ctx) as c:
        yield c


def add_span(name: str, ts: float, dur: float, *,
             ctx: Optional[TraceContext] = None, parent_id: str = "",
             span_id: str = "", **args) -> Optional[Span]:
    return TRACER.add_span(name, ts, dur, ctx=ctx, parent_id=parent_id,
                           span_id=span_id, **args)


def current_context() -> Optional[TraceContext]:
    """The global tracer's active context (thread-local, falling back to
    the process context from ``$KCTPU_TRACE_CONTEXT``)."""
    return TRACER.current_context()


# ---------------------------------------------------------------------------
# Cross-process dump/merge
# ---------------------------------------------------------------------------

def dump_to_env_dir(tracer: Optional[Tracer] = None) -> Optional[str]:
    """Dump the tracer to ``$KCTPU_TRACE_DIR`` (unique file per process);
    no-op (returns None) when the env var is unset or nothing was traced."""
    # `is None`, not `or`: an empty Tracer is falsy (len 0) but still the
    # caller's tracer — `or` would silently dump the global one instead.
    t = TRACER if tracer is None else tracer
    d = os.environ.get(TRACE_DIR_ENV, "")
    if not d or len(t) == 0:
        return None
    try:
        os.makedirs(d, exist_ok=True)
        path = os.path.join(d, f"trace-{os.getpid()}-{uuid.uuid4().hex[:8]}.json")
        t.dump(path)
        return path
    except OSError:
        return None  # tracing must never fail the workload


def load_trace_events(path: str) -> List[Dict[str, Any]]:
    """Read one Chrome trace JSON file's event list ([] on any damage)."""
    try:
        with open(path) as fh:
            doc = json.load(fh)
    except (OSError, ValueError):
        return []
    if isinstance(doc, list):  # bare-array Chrome trace flavor
        return [e for e in doc if isinstance(e, dict)]
    evs = doc.get("traceEvents") if isinstance(doc, dict) else None
    return [e for e in evs if isinstance(e, dict)] if isinstance(evs, list) else []


def merge_trace_dir(trace_dir: str,
                    tracer: Optional[Tracer] = None) -> Dict[str, Any]:
    """One Chrome trace document from every per-process dump in
    ``trace_dir`` plus (optionally) a live tracer's spans.  Deduplicated
    by span id: a process may dump more than once (explicit end-of-main
    dump + the zygote/atexit safety net), and the same span must not
    appear twice in the merged tree."""
    events: List[Dict[str, Any]] = []
    if trace_dir and os.path.isdir(trace_dir):
        for name in sorted(os.listdir(trace_dir)):
            if name.startswith("trace-") and name.endswith(".json"):
                events.extend(load_trace_events(os.path.join(trace_dir, name)))
    if tracer is not None:
        events.extend(s.to_event() for s in tracer.spans())
    seen: set = set()
    deduped: List[Dict[str, Any]] = []
    for e in events:
        span_id = event_ids(e)[1]
        key = span_id if span_id else id(e)
        if key in seen:
            continue
        seen.add(key)
        deduped.append(e)
    deduped.sort(key=lambda e: e.get("ts", 0))
    return {"traceEvents": deduped, "displayTimeUnit": "ms"}


# ---------------------------------------------------------------------------
# Causal-tree analysis (over merged Chrome events)
# ---------------------------------------------------------------------------

def event_ids(event: Dict[str, Any]) -> Tuple[str, str, str]:
    """(trace_id, span_id, parent_id) of a Chrome event ("" when absent)."""
    args = event.get("args") or {}
    if not isinstance(args, dict):
        return "", "", ""
    return (str(args.get("trace_id", "") or ""),
            str(args.get("span_id", "") or ""),
            str(args.get("parent_id", "") or ""))


def events_for_trace(events: List[Dict[str, Any]],
                     trace_id: str) -> List[Dict[str, Any]]:
    return [e for e in events if event_ids(e)[0] == trace_id]


def orphan_events(events: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Events whose parent_id names a span NOT present in the set — the
    broken-edge detector the continuity gate asserts is empty.  Roots
    (empty parent_id) are never orphans."""
    present = {event_ids(e)[1] for e in events}
    out = []
    for e in events:
        _, _, parent_id = event_ids(e)
        if parent_id and parent_id not in present:
            out.append(e)
    return out


def causal_tree(events: List[Dict[str, Any]]) -> Tuple[
        List[Dict[str, Any]], Dict[str, List[Dict[str, Any]]]]:
    """(roots, children-by-span_id), children in start-time order.  An
    orphan (missing parent) surfaces as a root so nothing disappears."""
    present = {event_ids(e)[1] for e in events}
    roots: List[Dict[str, Any]] = []
    children: Dict[str, List[Dict[str, Any]]] = {}
    for e in sorted(events, key=lambda ev: ev.get("ts", 0)):
        _, span_id, parent_id = event_ids(e)
        # A self-edge (parent_id == span_id) is a damaged root, not a
        # cycle — walk it as a root so the tree render terminates.
        if parent_id and parent_id != span_id and parent_id in present:
            children.setdefault(parent_id, []).append(e)
        else:
            roots.append(e)
    return roots, children


def render_timeline(events: List[Dict[str, Any]]) -> List[str]:
    """Human-readable causal timeline: one indented line per span with
    offset from the trace start and duration — what ``kctpu trace --job``
    prints."""
    if not events:
        return []
    t0 = min(e.get("ts", 0) for e in events)
    roots, children = causal_tree(events)
    lines: List[str] = []

    def walk(ev: Dict[str, Any], depth: int) -> None:
        off_ms = (ev.get("ts", 0) - t0) / 1000.0
        dur_ms = ev.get("dur", 0) / 1000.0
        args = ev.get("args") or {}
        extra = ""
        for k in ("key", "pod", "gang", "request"):
            if k in args:
                extra = f"  [{k}={args[k]}]"
                break
        lines.append(f"{'  ' * depth}{ev.get('name', '?'):<32s} "
                     f"+{off_ms:10.3f}ms  {dur_ms:10.3f}ms"
                     f"  pid={ev.get('pid', 0)}{extra}")
        _, span_id, _ = event_ids(ev)
        for child in children.get(span_id, []):
            walk(child, depth + 1)

    for root in roots:
        walk(root, 0)
    return lines


def _atexit_dump() -> None:  # pragma: no cover - exercised in subprocesses
    try:
        dump_to_env_dir()
    except Exception:
        pass


atexit.register(_atexit_dump)
