"""Per-replica and per-slice health.

The reference's checker is a 27-LoC classifier (pkg/checker/checker.go); the
north star asks for real health tracking with the TPU slice as the failure
domain (BASELINE.json, SURVEY.md §5 "failure detection").  This module turns
observed pods into a structured health report consumed by the updater (the
READY condition's message, updater/status.py) and the CLI ``describe``
Health section (cli/main.py:_describe_health).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Dict, List

from ..api.core import (
    PHASE_FAILED,
    PHASE_PENDING,
    PHASE_RUNNING,
    PHASE_SUCCEEDED,
    Pod,
    is_pod_active,
)
from ..api.tfjob import ReplicaType, TFJob
from ..planner.materialize import pods_by_index
from ..planner.plan import desired_replicas


class Health(str, enum.Enum):
    HEALTHY = "Healthy"        # all desired replicas active/succeeded
    DEGRADED = "Degraded"      # some replicas missing or restarting
    FAILED = "Failed"          # terminal failure present
    COMPLETE = "Complete"      # all replicas succeeded


@dataclass
class ReplicaHealth:
    type: ReplicaType
    desired: int
    running: int = 0
    waiting: int = 0
    succeeded: int = 0
    failed: int = 0
    missing_indices: List[int] = field(default_factory=list)
    health: Health = Health.DEGRADED


@dataclass
class JobHealth:
    replicas: Dict[ReplicaType, ReplicaHealth] = field(default_factory=dict)

    @property
    def overall(self) -> Health:
        states = [r.health for r in self.replicas.values()]
        if Health.FAILED in states:
            return Health.FAILED
        if all(s == Health.COMPLETE for s in states) and states:
            return Health.COMPLETE
        if Health.DEGRADED in states:
            return Health.DEGRADED
        return Health.HEALTHY


def check_health(job: TFJob, pods_by_type: Dict[ReplicaType, List[Pod]]) -> JobHealth:
    out = JobHealth()
    for spec in job.spec.tf_replica_specs:
        typ = spec.tf_replica_type
        desired = desired_replicas(spec)
        pods = pods_by_type.get(typ, [])
        rh = ReplicaHealth(type=typ, desired=desired)
        by_idx = pods_by_index(pods)
        for p in pods:
            if p.status.phase == PHASE_RUNNING:
                rh.running += 1
            elif p.status.phase == PHASE_PENDING:
                rh.waiting += 1
            elif p.status.phase == PHASE_SUCCEEDED:
                rh.succeeded += 1
            elif p.status.phase == PHASE_FAILED:
                rh.failed += 1
        for i in range(desired):
            plist = by_idx.get(i, [])
            if not any(is_pod_active(p) or p.status.phase == PHASE_SUCCEEDED for p in plist):
                rh.missing_indices.append(i)
        restart = spec.template.spec.restart_policy if spec.template else "OnFailure"
        replace = restart in ("OnFailure", "Always")
        succeeded_indices = sum(
            1 for i in range(desired)
            if any(p.status.phase == PHASE_SUCCEEDED for p in by_idx.get(i, []))
        )
        if rh.failed and not replace:
            rh.health = Health.FAILED
        elif typ != ReplicaType.PS and desired > 0 and succeeded_indices == desired:
            rh.health = Health.COMPLETE
        elif rh.missing_indices or rh.failed:
            # A TPU gang with any missing member is degraded as a whole —
            # the slice is one failure domain.
            rh.health = Health.DEGRADED
        else:
            rh.health = Health.HEALTHY
        out.replicas[typ] = rh
    return out
