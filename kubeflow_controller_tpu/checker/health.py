"""Per-replica and per-slice health.

The reference's checker is a 27-LoC classifier (pkg/checker/checker.go); the
north star asks for real health tracking with the TPU slice as the failure
domain (BASELINE.json, SURVEY.md §5 "failure detection").  This module turns
observed pods into a structured health report consumed by the updater (the
READY condition's message, updater/status.py) and the CLI ``describe``
Health section (cli/main.py:_describe_health).
"""

from __future__ import annotations

import enum
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..api.core import (
    PHASE_FAILED,
    PHASE_PENDING,
    PHASE_RUNNING,
    PHASE_SUCCEEDED,
    Pod,
    is_pod_active,
)
from ..api.tfjob import ReplicaType, TFJob
from ..obs.phases import STALL_HOLD_PHASES
from ..utils import locks
from ..planner.materialize import gang_width, pod_index, pods_by_index


class Health(str, enum.Enum):
    HEALTHY = "Healthy"        # all desired replicas active/succeeded
    DEGRADED = "Degraded"      # some replicas missing or restarting
    FAILED = "Failed"          # terminal failure present
    COMPLETE = "Complete"      # all replicas succeeded


@dataclass
class ReplicaHealth:
    type: ReplicaType
    desired: int
    running: int = 0
    waiting: int = 0
    succeeded: int = 0
    failed: int = 0
    missing_indices: List[int] = field(default_factory=list)
    # Indices whose training-plane heartbeat/step froze past the stall
    # deadline (only populated when check_health is given a StallTracker).
    stalled_indices: List[int] = field(default_factory=list)
    health: Health = Health.DEGRADED


@dataclass
class JobHealth:
    replicas: Dict[ReplicaType, ReplicaHealth] = field(default_factory=dict)

    @property
    def overall(self) -> Health:
        states = [r.health for r in self.replicas.values()]
        if Health.FAILED in states:
            return Health.FAILED
        if all(s == Health.COMPLETE for s in states) and states:
            return Health.COMPLETE
        if Health.DEGRADED in states:
            return Health.DEGRADED
        return Health.HEALTHY


# ---------------------------------------------------------------------------
# Training-plane stall detection
# ---------------------------------------------------------------------------

@dataclass
class StallPolicy:
    """Deadlines for declaring a Running replica's training stalled.

    Two independent signals (TF-Replicator/Podracer treat both as primary
    health — PAPERS.md): the *heartbeat* deadline fires when beats stop
    arriving at all (process hung/partitioned); the *step* deadline fires
    when beats keep arriving but the step counter freezes (rendezvous
    wedge, straggler stuck in a collective).  Either set to 0 disables
    that check."""

    heartbeat_deadline_s: float = 30.0
    step_deadline_s: float = 120.0
    # How often the controller re-enqueues progressing jobs so stalls are
    # noticed even though a stalled pod, by definition, generates no watch
    # events.  0 = derive from the deadlines.
    check_interval_s: float = 0.0
    # Drop per-pod step history not observed for this long (replaced pods
    # leave entries behind; generateName makes their keys unique forever).
    prune_after_s: float = 1800.0

    def effective_check_interval(self) -> float:
        if self.check_interval_s > 0:
            return self.check_interval_s
        deadlines = [d for d in (self.heartbeat_deadline_s,
                                 self.step_deadline_s) if d > 0]
        if not deadlines:
            return 30.0
        return max(0.05, min(deadlines) / 2.0)


class StallTracker:
    """Per-pod step-advancement memory + the stall verdict.

    Heartbeat staleness is stateless (``now - beat.timestamp``), but "the
    step counter stopped advancing" needs history: the tracker remembers,
    per pod, the last step seen and when it last *changed*.  Thread-safe —
    multiple sync workers observe concurrently."""

    def __init__(self, policy: Optional[StallPolicy] = None):
        self.policy = policy or StallPolicy()
        self._lock = locks.named_lock("checker.stall-tracker")
        # pod key -> (last step, wall clock when the step last advanced,
        #             wall clock of the last observation — for pruning,
        #             restoring: True while the pod is mid-restore)
        self._steps: Dict[str, Tuple[int, float, float, bool]] = {}

    def observe(self, key: str, progress, now: Optional[float] = None) -> bool:
        """Record one observation of a Running pod's progress; returns True
        when the pod is stalled under the policy."""
        t = now if now is not None else time.time()
        pol = self.policy
        stalled = False
        if (pol.heartbeat_deadline_s > 0
                and t - progress.timestamp > pol.heartbeat_deadline_s):
            stalled = True
        # A replica reporting phase="compile" freezes its step counter ON
        # PURPOSE (XLA is compiling; the reporter's keepalive keeps the
        # heartbeat fresh): keep resetting the advancement clock so the
        # frozen-step deadline neither fires mid-compile nor inherits the
        # whole compile as "time since last advance" once training starts.
        # The heartbeat deadline above still applies — a compile whose
        # process died stops beating and is flagged like any other hang.
        # phase="restore" gets the same hold: a replica restoring a
        # checkpoint after an in-place restart beats with a frozen (or
        # backward-jumped) step counter while Orbax reads the tree.
        # phase="reshard" (elastic plane) too: a width transition pauses
        # the step counter while survivors restore the checkpoint at the
        # new width and rebalance their data shards — long enough, it
        # would otherwise edge-trigger a spurious TrainingStalled.
        # Serving phases hold the deadline the same way: an
        # idle-but-healthy serving replica ("serving") freezes its decode
        # step counter BY DESIGN between requests, "load" is the model
        # load + AOT warmup window, and "drain" finishes in-flight work
        # with intake closed.  The heartbeat deadline still applies to
        # all of them — a dead server stops beating and is flagged.
        # The hold list is the shared registry's STALL_HOLD_PHASES
        # (obs/phases.py): one vocabulary for the stall detector, the
        # goodput ledger, and the phase-registry vet rule — a phase
        # typo'd at a beat site is flagged instead of silently losing
        # stall protection.
        held_phase = getattr(progress, "phase", "") in STALL_HOLD_PHASES
        with self._lock:
            last_step, advanced_at, _, restoring = self._steps.get(
                key, (None, 0.0, 0.0, False))
            if last_step is not None and progress.step < last_step:
                # Step DECREASED: an in-place restart resuming from an
                # older checkpoint, not a stall.  Enter the restore hold —
                # the frozen-step deadline stays parked until the counter
                # moves FORWARD again (mirroring the compile-phase hold);
                # the heartbeat deadline still applies throughout.
                restoring = True
            elif last_step is not None and progress.step > last_step:
                restoring = False  # training advanced: hold released
            if (last_step is None or progress.step != last_step
                    or held_phase or restoring):
                # First sighting, the counter moved, or a held phase:
                # the advancement clock is the beat's own time.
                advanced_at = progress.timestamp or t
            self._steps[key] = (progress.step, advanced_at, t, restoring)
            if len(self._steps) % 256 == 0:
                self._prune_locked(t)
        if (not stalled and pol.step_deadline_s > 0
                and t - advanced_at > pol.step_deadline_s):
            stalled = True
        return stalled

    def forget(self, key: str) -> None:
        with self._lock:
            self._steps.pop(key, None)

    def _prune_locked(self, now: float) -> None:
        cutoff = now - self.policy.prune_after_s
        for k in [k for k, (_, _, seen, _) in self._steps.items()
                  if seen < cutoff]:
            del self._steps[k]

    def __len__(self) -> int:
        with self._lock:
            return len(self._steps)


def check_health(job: TFJob, pods_by_type: Dict[ReplicaType, List[Pod]],
                 now: Optional[float] = None,
                 tracker: Optional[StallTracker] = None,
                 exhausted: Optional[Dict[ReplicaType, set]] = None) -> JobHealth:
    """``exhausted`` (optional): replica indices whose restart budget the
    recovery policy has spent — failures there are terminal even under a
    replace-on-failure restart policy."""
    out = JobHealth()
    exhausted = exhausted or {}
    for spec in job.spec.tf_replica_specs:
        typ = spec.tf_replica_type
        # Elastic gangs are measured against their CURRENT width: a
        # degraded gang whose every current member runs is Healthy here
        # (the reduced width is the job-level Degraded condition's story).
        desired = gang_width(job, spec)
        pods = pods_by_type.get(typ, [])
        rh = ReplicaHealth(type=typ, desired=desired)
        by_idx = pods_by_index(pods)
        if tracker is not None:
            for p in pods:
                if (p.status.phase == PHASE_RUNNING
                        and p.status.progress is not None
                        and tracker.observe(
                            f"{p.metadata.namespace}/{p.metadata.name}",
                            p.status.progress, now=now)):
                    idx = pod_index(p)
                    if idx is not None:
                        rh.stalled_indices.append(idx)
            rh.stalled_indices.sort()
        for p in pods:
            if p.status.phase == PHASE_RUNNING:
                rh.running += 1
            elif p.status.phase == PHASE_PENDING:
                rh.waiting += 1
            elif p.status.phase == PHASE_SUCCEEDED:
                rh.succeeded += 1
            elif p.status.phase == PHASE_FAILED:
                rh.failed += 1
        for i in range(desired):
            plist = by_idx.get(i, [])
            if not any(is_pod_active(p) or p.status.phase == PHASE_SUCCEEDED for p in plist):
                rh.missing_indices.append(i)
        restart = spec.template.spec.restart_policy if spec.template else "OnFailure"
        replace = restart in ("OnFailure", "Always")
        succeeded_indices = sum(
            1 for i in range(desired)
            if any(p.status.phase == PHASE_SUCCEEDED for p in by_idx.get(i, []))
        )
        if rh.failed and (not replace or exhausted.get(typ)):
            # Terminal by policy: restartPolicy Never, or the recovery
            # plane's backoff limit is exhausted for an index of this type.
            rh.health = Health.FAILED
        elif typ != ReplicaType.PS and desired > 0 and succeeded_indices == desired:
            rh.health = Health.COMPLETE
        elif rh.missing_indices or rh.failed or rh.stalled_indices:
            # A TPU gang with any missing member is degraded as a whole —
            # the slice is one failure domain.  A stalled member degrades
            # the gang the same way: synchronous collectives advance at
            # the pace of the slowest process.
            rh.health = Health.DEGRADED
        else:
            rh.health = Health.HEALTHY
        out.replicas[typ] = rh
    return out
