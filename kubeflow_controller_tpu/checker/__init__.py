"""Job classification and health tracking (ref: pkg/checker/checker.go, grown
into the real health tracker SURVEY.md §7 step 5 calls for)."""

from ..api.tfjob import is_local_job, is_tpu_job  # noqa: F401
from .health import (  # noqa: F401
    JobHealth,
    ReplicaHealth,
    StallPolicy,
    StallTracker,
    check_health,
)
