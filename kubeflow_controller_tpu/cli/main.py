"""tfjob-controller CLI — the process shell.

Flag parity with the reference binary (ref: cmd/controller/main.go:76-78:
``-kubeconfig``, ``-master``, ``-version``; version/GitSHA banner at
main.go:85-88; two workers at main.go:70; 30s resync at main.go:62-63),
adapted to this framework's substrate: with no cluster available the
controller runs against the in-memory API server (``--in-memory``), applying
job manifests from files, driving them with the fake kubelet (optionally
executing container commands as real local subprocesses), and reporting
status/events/metrics.

Usage:
    python -m kubeflow_controller_tpu.cli version
    python -m kubeflow_controller_tpu.cli run --in-memory \
        --manifests examples/jobs/ --execute --until-done
    python -m kubeflow_controller_tpu.cli validate -f job.yaml
"""

from __future__ import annotations

import argparse
import json
import logging
import pathlib
import sys
import time
from typing import List

import yaml

from .. import GIT_SHA, __version__
from ..api.tfjob import TFJob, TFJobPhase, validate_tfjob, ValidationError
from ..cluster import Cluster, FakeKubelet, PhasePolicy, TPUInventory, TPUSlice
from ..controller import Controller
from ..utils import serde
from .signals import setup_signal_handler

logger = logging.getLogger("kubeflow_controller_tpu.cli")


def load_manifests(paths: List[str]) -> List[TFJob]:
    jobs = []
    files: List[pathlib.Path] = []
    for p in paths:
        path = pathlib.Path(p)
        if path.is_dir():
            files.extend(sorted(path.glob("*.y*ml")) + sorted(path.glob("*.json")))
        else:
            files.append(path)
    for f in files:
        with open(f) as fh:
            docs = list(yaml.safe_load_all(fh)) if f.suffix != ".json" else [json.load(fh)]
        for doc in docs:
            if not doc:
                continue
            job = serde.from_dict(TFJob, doc)
            jobs.append(job)
    return jobs


def cmd_version(_args) -> int:
    print(f"tfjob-controller version {__version__}, git sha {GIT_SHA}, "
          f"python {sys.version.split()[0]}")
    return 0


def cmd_validate(args) -> int:
    rc = 0
    try:
        jobs = load_manifests(args.files)
    except (OSError, yaml.YAMLError, ValueError, TypeError) as e:
        # ValueError/TypeError: serde-level schema errors (bad enum, wrong
        # field shape) — the very input class `validate` exists to diagnose.
        print(f"error loading manifests: {e}", file=sys.stderr)
        return 1
    for job in jobs:
        name = job.metadata.name or job.metadata.generate_name or "<unnamed>"
        try:
            validate_tfjob(job)
            print(f"{name}: OK")
        except ValidationError as e:
            print(f"{name}: INVALID: {e}")
            rc = 1
    return rc


def cmd_run(args) -> int:
    logging.basicConfig(
        level=logging.DEBUG if args.v >= 4 else logging.INFO,
        format="%(asctime)s %(levelname).1s %(name)s: %(message)s",
    )
    if not args.in_memory:
        print("error: only --in-memory mode is available in this environment "
              "(no kubeconfig/cluster support compiled in); pass --in-memory",
              file=sys.stderr)
        return 2

    stop = setup_signal_handler()
    cluster = Cluster()
    slices = [
        TPUSlice(f"slice-{i}", args.tpu_slice_type, num_hosts=args.tpu_slice_hosts)
        for i in range(args.tpu_slices)
    ]
    inventory = TPUInventory(slices)
    kubelet = FakeKubelet(
        cluster,
        policy=PhasePolicy(run_s=args.sim_run_seconds),
        inventory=inventory,
        execute=args.execute,
    )
    ctrl = Controller(cluster, inventory=inventory, resync_period_s=args.resync_period)
    kubelet.start()
    ctrl.run(threadiness=args.threadiness)
    logger.info("tfjob-controller %s (git %s) started: %d workers, %.0fs resync",
                __version__, GIT_SHA, args.threadiness, args.resync_period)

    terminal = (TFJobPhase.SUCCEEDED, TFJobPhase.FAILED)
    jobs = []
    try:
        try:
            jobs = load_manifests(args.manifests) if args.manifests else []
        except (OSError, yaml.YAMLError, ValueError, TypeError) as e:
            print(f"error loading manifests: {e}", file=sys.stderr)
            return 1
        for job in jobs:
            created = cluster.tfjobs.create(job)
            logger.info("applied TFJob %s/%s", created.metadata.namespace or "default",
                        created.metadata.name)
        while not stop.is_set():
            time.sleep(0.2)
            if args.until_done and jobs:
                all_jobs = cluster.tfjobs.list()
                if all_jobs and all(j.status.phase in terminal for j in all_jobs):
                    break
    finally:
        ctrl.stop()
        kubelet.stop()

    rc = 0
    for j in cluster.tfjobs.list():
        key = f"{j.metadata.namespace}/{j.metadata.name}"
        print(f"{key}: phase={j.status.phase.value}")
        for rs in j.status.tf_replica_statuses:
            hist = {k.value: v for k, v in rs.tf_replicas_states.items()}
            print(f"  {rs.type.value}: state={rs.state.value} pods={len(rs.pod_names)} {hist}")
        if args.events:
            for e in ctrl.recorder.events_for(j.metadata.namespace, j.metadata.name):
                print(f"  event {e.type} {e.reason}: {e.message} (x{e.count})")
        if j.status.phase == TFJobPhase.FAILED:
            rc = 3
    snap = ctrl.metrics.snapshot()
    print(f"metrics: syncs={snap['syncs']} errors={snap['sync_errors']} "
          f"creates={snap['creates']} deletes={snap['deletes']} "
          f"reconcile_p50={snap['reconcile_p50_s'] * 1e3:.2f}ms "
          f"p99={snap['reconcile_p99_s'] * 1e3:.2f}ms")
    return rc


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="tfjob-controller",
                                description="TPU-native TFJob controller")
    p.add_argument("-version", "--version", action="store_true",
                   help="print version and exit (ref flag parity)")
    p.add_argument("-kubeconfig", "--kubeconfig", default="",
                   help="path to a kubeconfig (reserved; real-cluster mode "
                        "is not compiled into this build)")
    p.add_argument("-master", "--master", default="",
                   help="API server address override (reserved, as above)")
    sub = p.add_subparsers(dest="cmd")

    sub.add_parser("version", help="print version and exit")

    v = sub.add_parser("validate", help="validate TFJob manifests")
    v.add_argument("-f", "--files", nargs="+", required=True)

    r = sub.add_parser("run", help="run the controller")
    r.add_argument("--in-memory", action="store_true",
                   help="run against the in-memory cluster substrate")
    r.add_argument("--manifests", nargs="*", default=[],
                   help="TFJob manifest files/dirs to apply at startup")
    r.add_argument("--execute", action="store_true",
                   help="kubelet executes container commands as local processes")
    r.add_argument("--until-done", action="store_true",
                   help="exit once every applied job reaches a terminal phase")
    r.add_argument("--events", action="store_true", help="print per-job events at exit")
    r.add_argument("--threadiness", type=int, default=2, help="sync workers (ref: 2)")
    r.add_argument("--resync-period", type=float, default=30.0, help="informer resync (ref: 30s)")
    r.add_argument("--sim-run-seconds", type=float, default=0.05,
                   help="simulated pod run time when not using --execute")
    r.add_argument("--tpu-slices", type=int, default=1, help="fake TPU slices in inventory")
    r.add_argument("--tpu-slice-type", default="v5e-8")
    r.add_argument("--tpu-slice-hosts", type=int, default=2)
    r.add_argument("-v", type=int, default=0, help="log verbosity (glog parity)")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.version or args.cmd == "version":
        return cmd_version(args)
    if args.cmd == "validate":
        return cmd_validate(args)
    if args.cmd == "run":
        return cmd_run(args)
    build_parser().print_help()
    return 0
