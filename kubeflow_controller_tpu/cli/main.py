"""tfjob-controller CLI — the process shell.

Flag parity with the reference binary (ref: cmd/controller/main.go:76-78:
``-kubeconfig``, ``-master``, ``-version``; version/GitSHA banner at
main.go:85-88; two workers at main.go:70; 30s resync at main.go:62-63),
adapted to this framework's substrate: with no cluster available the
controller runs against the in-memory API server (``--in-memory``), applying
job manifests from files, driving them with the fake kubelet (optionally
executing container commands as real local subprocesses), and reporting
status/events/metrics.

Usage:
    python -m kubeflow_controller_tpu.cli version
    python -m kubeflow_controller_tpu.cli run --in-memory \
        --manifests examples/jobs/ --execute --until-done
    python -m kubeflow_controller_tpu.cli validate -f job.yaml

Real-cluster (two-process) mode — the controller speaks HTTP to an API
server, exactly the reference's deployment shape:
    python -m kubeflow_controller_tpu.cli serve --port 8081 &
    python -m kubeflow_controller_tpu.cli -master http://127.0.0.1:8081 run \
        --manifests examples/jobs/local.yaml --until-done
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import pathlib
import sys
import time
from typing import List

import yaml

from .. import GIT_SHA, __version__
from ..api.tenant import tenant_of
from ..api.tfjob import TFJob, TFJobPhase, validate_tfjob, ValidationError
from ..cluster import Cluster, FakeKubelet, PhasePolicy, TPUInventory, TPUSlice
from ..cluster.store import APIError
from ..controller import Controller
from ..utils import serde
from .signals import setup_signal_handler

logger = logging.getLogger("kubeflow_controller_tpu.cli")


def load_manifests(paths: List[str]) -> List[TFJob]:
    jobs = []
    files: List[pathlib.Path] = []
    for p in paths:
        path = pathlib.Path(p)
        if path.is_dir():
            files.extend(sorted(path.glob("*.y*ml")) + sorted(path.glob("*.json")))
        else:
            files.append(path)
    for f in files:
        with open(f) as fh:
            docs = list(yaml.safe_load_all(fh)) if f.suffix != ".json" else [json.load(fh)]
        for doc in docs:
            if not doc:
                continue
            job = serde.from_dict(TFJob, doc)
            jobs.append(job)
    return jobs


def cmd_version(_args) -> int:
    print(f"tfjob-controller version {__version__}, git sha {GIT_SHA}, "
          f"python {sys.version.split()[0]}")
    return 0


def cmd_validate(args) -> int:
    rc = 0
    try:
        jobs = load_manifests(args.files)
    except (OSError, yaml.YAMLError, ValueError, TypeError) as e:
        # ValueError/TypeError: serde-level schema errors (bad enum, wrong
        # field shape) — the very input class `validate` exists to diagnose.
        print(f"error loading manifests: {e}", file=sys.stderr)
        return 1
    for job in jobs:
        name = job.metadata.name or job.metadata.generate_name or "<unnamed>"
        try:
            validate_tfjob(job)
            print(f"{name}: OK")
        except ValidationError as e:
            print(f"{name}: INVALID: {e}")
            rc = 1
    return rc


def _build_substrate(args, cluster):
    """The fake-cluster node side shared by `serve` and `run --in-memory`:
    TPU inventory from the flags, wrapped in the gang scheduler (priority
    queue + preemption + backfill; `--no-sched` keeps the first-come
    baseline), + a kubelet driving the given cluster."""
    slices = [
        TPUSlice(f"slice-{i}", args.tpu_slice_type, num_hosts=args.tpu_slice_hosts)
        for i in range(args.tpu_slices)
    ]
    inventory = TPUInventory(slices)
    if not getattr(args, "no_sched", False):
        from ..scheduler import GangScheduler, SchedulerPolicy

        inventory = GangScheduler(inventory, SchedulerPolicy(
            preemption=not getattr(args, "no_preemption", False)))
    kubelet = FakeKubelet(
        cluster,
        policy=PhasePolicy(run_s=args.sim_run_seconds),
        inventory=inventory,
        execute=args.execute,
    )
    return inventory, kubelet


def cmd_serve(args) -> int:
    """Run the in-memory API server (+ kubelet) as a standalone process —
    the cluster half of real-cluster mode.  A controller in another process
    connects with ``run -master http://127.0.0.1:<port>``."""
    from ..cluster.apiserver import FakeAPIServer
    from ..cluster.store import ObjectStore

    logging.basicConfig(
        level=logging.DEBUG if args.v >= 4 else logging.INFO,
        format="%(asctime)s %(levelname).1s %(name)s: %(message)s",
    )
    stop = setup_signal_handler()
    if args.wal_dir:
        # Durable mode (docs/HA.md): recover WAL-over-snapshot (an empty
        # directory recovers to an empty store), then keep journaling.
        # A restarted `serve` on the same directory comes back
        # RV-identical — watch clients resume, nothing re-lists.
        from ..cluster.store import ObjectStore as _Store
        from ..ha.wal import WriteAheadLog

        store = _Store.recover(WriteAheadLog(args.wal_dir))
        print(f"recovered store from {args.wal_dir} "
              f"(rv {store.export_state()['rv']})", flush=True)
    else:
        store = ObjectStore()
    _, kubelet = _build_substrate(args, Cluster(store=store))
    server = FakeAPIServer(store, token=args.token, port=args.port,
                           kubelet=kubelet)
    url = server.start()
    kubelet.start()
    print(f"api server listening on {url}", flush=True)
    try:
        while not stop.is_set():
            time.sleep(0.2)
    finally:
        kubelet.stop()
        server.stop()
    return 0


def _rest_cluster_or_die(args, probe: bool = True):
    """Build the REST cluster; with ``probe`` a cheap connectivity check
    fails fast (used by `run`, whose informers would otherwise block).
    Read-only commands skip the probe — their first real request plays
    that role — and handle APIError themselves."""
    from ..cluster.rest import KubeconfigError, RestCluster

    try:
        cluster = RestCluster.from_flags(args.kubeconfig, args.master)
        if probe:
            cluster.tfjobs.list()
        return cluster
    except (KubeconfigError, OSError, APIError) as e:
        print(f"error talking to API server: {e}", file=sys.stderr)
        return None


def _age(seconds: float) -> str:
    """kubectl-style compact age: 5s / 2m10s / 1h2m / 3d."""
    s = max(0, int(seconds))
    if s < 60:
        return f"{s}s"
    m, s = divmod(s, 60)
    if m < 60:
        return f"{m}m{s}s" if s else f"{m}m"
    h, m = divmod(m, 60)
    if h < 24:
        return f"{h}h{m}m" if m else f"{h}h"
    d, h = divmod(h, 24)
    return f"{d}d{h}h" if h else f"{d}d"


def _progress_cells(j) -> tuple:
    """(STEP, RATE) cells for a job row: job-level step (min across
    replicas) and summed examples/sec; '-' before any heartbeat."""
    p = j.status.progress
    if p is None:
        return "-", "-"
    step = str(p.step) if p.step == p.max_step else f"{p.step}..{p.max_step}"
    if p.stalled:
        step += "!"
    return step, f"{p.examples_per_sec:g}"


def _serving_cells(j) -> tuple:
    """(QPS, TTFT) cells for a job row — serving jobs only, '-' elsewhere
    (QPS = summed completed requests/sec across ready replicas; TTFT is
    the worst replica's windowed p50/p99 pair — the p99 half is the
    histogram-derived quantile the serving-ttft-p99 SLO burns against)."""
    sv = j.status.serving
    if sv is None:
        return "-", "-"
    if sv.ttft_p99_ms:
        return f"{sv.qps:g}", f"{sv.ttft_ms:g}/{sv.ttft_p99_ms:g}ms"
    return f"{sv.qps:g}", f"{sv.ttft_ms:g}ms"


def _gateway_stats(j) -> dict:
    """The gateway's published data-plane snapshot off the job's
    gateway-stats annotation ({} when absent or unparseable) — the same
    payload the autoscaler folds into its scale signal."""
    from ..api.labels import ANNOTATION_GATEWAY_STATS

    raw = j.metadata.annotations.get(ANNOTATION_GATEWAY_STATS, "")
    if not raw:
        return {}
    try:
        d = json.loads(raw)
    except ValueError:
        return {}
    return d if isinstance(d, dict) else {}


def _gateway_cells(j) -> tuple:
    """(GWQPS, HIT) cells for a `top` row: the gateway's routed QPS and
    the routed-weighted prefix-cache hit ratio ('-' without a gateway)."""
    d = _gateway_stats(j)
    if not d:
        return "-", "-"
    qps = f"{float(d.get('qps', 0.0) or 0.0):g}"
    hit = f"{float(d.get('prefix_hit_ratio', 0.0) or 0.0):.0%}"
    return qps, hit


def _placement(j) -> dict:
    """The scheduler's placement record off the job's placement annotation
    ({} when absent or unparseable): bound slices, DCN domains spanned,
    adjacency score, mesh axis -> scope map."""
    from ..api.labels import ANNOTATION_PLACEMENT

    raw = j.metadata.annotations.get(ANNOTATION_PLACEMENT, "")
    if not raw:
        return {}
    try:
        d = json.loads(raw)
    except ValueError:
        return {}
    return d if isinstance(d, dict) else {}


def _alert_banner(cluster) -> str:
    """One-line firing-SLO summary for the ``get`` header ('' when quiet
    or the server has no SLO surface)."""
    try:
        doc = cluster.debug_slos()
    except (APIError, AttributeError):
        return ""
    active = [a for a in doc.get("alerts", []) if a.get("active")]
    if not active:
        return ""
    parts = []
    for a in active[:4]:
        labels = a.get("labels") or {}
        subj = (labels.get("tfjob")
                or ",".join(f"{k}={v}" for k, v in sorted(labels.items()))
                or "cluster")
        parts.append(f"{a['slo']}({subj}) {a.get('burn_fast', 0):g}x")
    more = f" +{len(active) - 4} more" if len(active) > 4 else ""
    return f"SLO BURN: {', '.join(parts)}{more}  (kctpu alerts)"


def _fetch_lease(cluster):
    """The controller leader lease, or None (no HA control plane / server
    unreachable) — what `get`/`describe`/`top` surface leadership from."""
    from ..ha.lease import LEASE_NAME, LEASE_NAMESPACE

    try:
        return cluster.leases.get(LEASE_NAMESPACE, LEASE_NAME)
    except APIError:
        return None


def _lease_live(lease) -> bool:
    held_until = (max(lease.spec.renew_time, lease.spec.acquire_time)
                  + lease.spec.lease_duration_s)
    return bool(lease.spec.holder_identity) and time.time() < held_until


def _leader_line(lease) -> str:
    """One-line leadership summary: holder, generation (= fencing token),
    shard count, and lease freshness."""
    if lease is None:
        return ""
    if not _lease_live(lease):
        return (f"leader: <none> (lease expired; last holder "
                f"{lease.spec.holder_identity or '<none>'}, "
                f"generation {lease.spec.generation})")
    age = max(0.0, time.time() - lease.spec.renew_time)
    return (f"leader: {lease.spec.holder_identity} "
            f"(generation {lease.spec.generation}, "
            f"{lease.spec.shards} controller shard(s), "
            f"renewed {age:.1f}s ago)")


def _shard_cell(job, lease) -> str:
    """The owning controller shard for a job, recomputed from the lease's
    advertised shard count over the job's UID — the same hash ring the
    controller routes by (ha/ring.py)."""
    from ..ha.ring import shard_of

    if lease is None or lease.spec.shards <= 1:
        return "-"
    s = shard_of(job.metadata.uid or job.metadata.name, lease.spec.shards)
    return str(s) if s is not None else "-"


def cmd_get(args) -> int:
    """kubectl-get analog: one line per TFJob (REST mode only)."""
    cluster = _rest_cluster_or_die(args, probe=False)
    if cluster is None:
        return 2
    try:
        jobs = cluster.tfjobs.list(args.namespace or None)
    except APIError as e:
        print(f"error talking to API server: {e}", file=sys.stderr)
        return 2
    lease = _fetch_lease(cluster)
    if lease is not None:
        print(_leader_line(lease))
    banner = _alert_banner(cluster)
    if banner:
        print(banner)
    # Tenancy filter resolves through tenant_of (label override, then
    # namespace) — the same identity the scheduler queues by.
    if getattr(args, "tenant", ""):
        jobs = [j for j in jobs if tenant_of(j) == args.tenant]
    if not jobs:
        print("No resources found.")
        return 0
    print(f"{'NAMESPACE':<12} {'TENANT':<12} {'NAME':<32} {'PHASE':<12} "
          f"{'REASON':<28} {'STEP':<10} {'RATE':<10} {'QPS':<8} {'TTFT':<9} "
          f"{'RESTARTS':<9} {'SHARD':<6} REPLICAS")
    for j in jobs:
        kinds = ",".join(
            f"{s.tf_replica_type.value}x{s.replicas}" for s in j.spec.tf_replica_specs
        )
        # Elastic width, when it differs from spec: "Workerx3[w=2]".
        w = j.status.width
        if w is not None and w.current < w.spec:
            kinds += f"[w={w.current}]"
        # Multislice placement, when bound: "TPUx8[slices=4]".
        pl = _placement(j)
        if pl.get("slices"):
            kinds += f"[slices={len(pl['slices'])}]"
        # Serving scale, when live: "Servingx1[s=3/3]" (current/ready).
        sv = j.status.serving
        if sv is not None and sv.replicas:
            kinds += f"[s={sv.ready}/{sv.replicas}]"
        # Goodput ratio for running training jobs: "Workerx2[good=85%]"
        # (share of occupied time spent training; obs/goodput.py ledger).
        gp = j.status.goodput
        if (gp is not None and gp.occupied_s > 0
                and j.status.phase.value == "Running"
                and j.status.progress is not None):
            kinds += f"[good={gp.ratio:.0%}]"
        # Gateway front door, when publishing: routed QPS, prefix-cache
        # hit ratio, and total sheds (the overload tell).
        gw = _gateway_stats(j)
        if gw:
            shed = sum(int(v) for v in (gw.get("shed") or {}).values())
            kinds += (f"[gw={float(gw.get('qps', 0) or 0):g}qps "
                      f"hit={float(gw.get('prefix_hit_ratio', 0) or 0):.0%}"
                      + (f" shed={shed}" if shed else "") + "]")
        # kubectl parity: deletionTimestamp set -> Terminating (a job stays
        # in this state until a running controller processes its finalizer).
        phase = ("Terminating" if j.metadata.deletion_timestamp is not None
                 else j.status.phase.value)
        # Why a Pending job is pending: queue position under slice
        # contention ("GangQueued: position 2/5 ..."), else any status
        # reason, compacted to the column.
        reason = (j.status.reason or "-").replace("GangQueued: ", "queued: ")
        if len(reason) > 27:
            reason = reason[:26] + "…"
        step, rate = _progress_cells(j)
        qps, ttft = _serving_cells(j)
        # kubectl RESTARTS parity: the recovery plane's monotonic restart
        # total across every replica of the job.
        restarts = sum(rs.restarts for rs in j.status.tf_replica_statuses)
        print(f"{j.metadata.namespace:<12} {tenant_of(j):<12} "
              f"{j.metadata.name:<32} "
              f"{phase:<12} {reason:<28} {step:<10} {rate:<10} "
              f"{qps:<8} {ttft:<9} "
              f"{restarts:<9} {_shard_cell(j, lease):<6} {kinds}")
    return 0


def cmd_describe(args) -> int:
    """kubectl-describe analog: spec summary, status rollup, child pods,
    and the job's Event objects (REST mode only)."""
    from ..cluster.store import NotFound

    cluster = _rest_cluster_or_die(args, probe=False)
    if cluster is None:
        return 2
    ns = args.namespace or "default"
    try:
        j = cluster.tfjobs.get(ns, args.name)
    except NotFound:
        print(f"tfjob {ns}/{args.name} not found", file=sys.stderr)
        return 1
    except APIError as e:
        print(f"error talking to API server: {e}", file=sys.stderr)
        return 2
    print(f"Name:      {j.metadata.name}")
    print(f"Namespace: {j.metadata.namespace}")
    print(f"RuntimeID: {j.spec.runtime_id}")
    _describe_tenant(cluster, j)
    lease = _fetch_lease(cluster)
    if lease is not None:
        print(f"Leader:    {_leader_line(lease).removeprefix('leader: ')}")
        shard = _shard_cell(j, lease)
        if shard != "-":
            print(f"Shard:     {shard} of {lease.spec.shards} "
                  f"(consistent hash of uid {j.metadata.uid})")
    print(f"Phase:     {j.status.phase.value}"
          + (f"  ({j.status.reason})" if j.status.reason else ""))
    if j.status.width is not None:
        w = j.status.width
        tag = "  DEGRADED (replacement warming)" if w.current < w.spec else ""
        print(f"Width:     {w.current}/{w.spec} (elastic floor {w.min}){tag}")
    _describe_placement(j)
    _describe_serving(j)
    _describe_gateway(j)
    if j.status.reason.startswith("GangQueued"):
        print(f"Queue:     {j.status.reason}")
    for c in j.status.conditions:
        msg = f"  {c.message}" if c.reason in ("GangQueued", "GangPreempted") and c.message else ""
        print(f"Condition: {c.type.value}={c.status} {c.reason}{msg}")
    for rs in j.status.tf_replica_statuses:
        hist = {k.value: v for k, v in rs.tf_replicas_states.items()}
        restarts = f" restarts={rs.restarts}" if rs.restarts else ""
        print(f"Replicas:  {rs.type.value}: state={rs.state.value} "
              f"{hist}{restarts}")
        for pn in rs.pod_names:
            print(f"           pod {pn}")
    _describe_health(cluster, j, ns)
    _describe_compile_cache(j)
    _describe_progress(j)
    _describe_goodput(j)
    try:
        events = [e for e in cluster.events.list(ns)
                  if e.involved_object.name == args.name]
    except APIError:
        events = []  # server lost mid-describe: show what we have
    if events:
        now = time.time()
        print("Events:")
        # Newest activity last (kubectl ordering); AGE is last-seen
        # relative time, so a count-aggregated repeating event reads as
        # current, not as old as its first sighting.
        for e in sorted(events, key=lambda e: e.last_timestamp or e.first_timestamp):
            age = _age(now - (e.last_timestamp or e.first_timestamp))
            print(f"  {age:>6}  {e.type:<8} {e.reason:<18} x{e.count}  {e.message}")
    return 0


def _tenant_gauges(cluster) -> dict:
    """Per-tenant scheduler gauges scraped from /metrics: tenant ->
    {"share": float, "borrowed": int}.  Empty when the serve process
    predates the tenancy plane or metrics are unreachable."""
    import re

    out: dict = {}
    try:
        text = cluster.metrics_text()
    except (APIError, AttributeError):
        return out
    pat = re.compile(
        r'^kctpu_sched_tenant_(share|borrowed_slices)'
        r'\{tenant="([^"]+)"\}\s+([0-9.eE+-]+)')
    for line in text.splitlines():
        m = pat.match(line)
        if not m:
            continue
        kind, tenant, val = m.group(1), m.group(2), float(m.group(3))
        d = out.setdefault(tenant, {})
        if kind == "share":
            d["share"] = val
        else:
            d["borrowed"] = int(val)
    return out


def _describe_tenant(cluster, j) -> None:
    """Quota/Share section: the job's resolved tenant, its TenantQuota
    spec (when one exists), and the scheduler's live dominant share +
    borrowed-slice count for that tenant (scraped from /metrics)."""
    tenant = tenant_of(j)
    print(f"Tenant:    {tenant}")
    quota = None
    try:
        for q in cluster.tenantquotas.list(None):
            if q.metadata.name == tenant:
                quota = q
                break
    except (APIError, AttributeError):
        pass
    if quota is not None:
        sp = quota.spec
        caps = []
        if sp.slices:
            caps.append(f"slices={sp.slices}")
        if sp.serving_replicas:
            caps.append(f"serving={sp.serving_replicas}")
        print(f"Quota:     weight={sp.weight:g}"
              + ("".join(" " + c for c in caps))
              + ("" if sp.borrowable else "  (non-borrowable)"))
    g = _tenant_gauges(cluster).get(tenant)
    if g is not None:
        borrowed = g.get("borrowed", 0)
        print(f"Share:     dominant={g.get('share', 0.0):.3f}"
              + (f"  borrowed={borrowed} slice(s)" if borrowed else ""))


def _describe_placement(j) -> None:
    """Placement section off the placement annotation: the bound slice
    set, the DCN domains it spans (with the adjacency score — 1.0 means
    one domain), and where each mesh axis lives (dcn vs ici)."""
    d = _placement(j)
    if not d.get("slices"):
        return
    slices = d["slices"]
    domains = d.get("domains") or []
    score = float(d.get("score", 1.0) or 1.0)
    print(f"Placement: {len(slices)} slice(s) across "
          f"{len(domains) or 1} DCN domain(s), adjacency={score:g}")
    print(f"           slices: {', '.join(slices)}")
    if domains:
        print(f"           domains: {', '.join(domains)}")
    mesh = d.get("mesh") or {}
    if mesh:
        cells = " ".join(f"{axis}->{mesh[axis]}" for axis in sorted(mesh))
        print(f"           mesh: {cells}")


def _describe_serving(j) -> None:
    """Serving section: replicas ready vs the autoscaler's target, live
    throughput/latency, batch occupancy, and the autoscale bounds."""
    sv = j.status.serving
    if sv is None:
        return
    bounds = (f"autoscale {sv.min_replicas}..{sv.max_replicas} "
              f"@ queue depth {sv.target_queue_depth:g}"
              if sv.max_replicas else "fixed scale")
    print(f"Serving:   {sv.ready}/{sv.replicas} replicas ready ({bounds})")
    if sv.ready:
        print(f"           qps={sv.qps:g} ttft(p50)={sv.ttft_ms:g}ms "
              f"itl={sv.itl_ms:g}ms queue={sv.queue_depth} "
              f"occupancy={sv.occupancy:.0%}")


def _describe_gateway(j) -> None:
    """Gateway front-door section off the gateway-stats annotation:
    routed QPS + end-to-end p99 TTFT, admission pressure, shed counts per
    tier, prefix-cache hit ratio, and per-replica routing weights (what
    'least-loaded with affinity' actually converged to)."""
    d = _gateway_stats(j)
    if not d:
        return
    print(f"Gateway:   qps={float(d.get('qps', 0) or 0):g} "
          f"ttft(p99)={float(d.get('ttft_p99_ms', 0) or 0):g}ms "
          f"queued={int(d.get('queued', 0) or 0)} "
          f"pressure={float(d.get('pressure', 0) or 0):.2f} "
          f"prefix-hit={float(d.get('prefix_hit_ratio', 0) or 0):.0%}")
    shed = d.get("shed") or {}
    rerouted = int(d.get("rerouted", 0) or 0)
    if shed or rerouted:
        cells = " ".join(f"{t}={shed[t]}" for t in sorted(shed))
        line = f"           shed: {cells or 'none'}"
        if float(d.get("shed_rps", 0) or 0):
            line += f" ({float(d['shed_rps']):g}/s)"
        if rerouted:
            line += f"  rerouted={rerouted} (drain re-homes)"
        print(line)
    weights = d.get("weights") or {}
    if weights:
        cells = " ".join(f"{name}={float(weights[name]):.0%}"
                         for name in sorted(weights))
        print(f"           weights: {cells}")


def _describe_compile_cache(j) -> None:
    """Compile-cache state: the spec-pinned dir (with an entry census when
    it is statable from here — single-node fake clusters share the
    filesystem) and each reporting replica's executable provenance."""
    d = j.spec.compile_cache_dir
    p = j.status.progress
    sources = {}
    if p is not None:
        for r in p.replicas:
            if r.compile_source:
                sources[r.compile_source] = sources.get(r.compile_source, 0) + 1
    if not d and not sources:
        return
    line = "CompileCache:"
    if d:
        line += f" {d}"
        if os.path.isdir(d):
            from ..workloads.compile_cache import cache_entries

            n = cache_entries(d)
            line += f" ({n['aot']} aot / {n['xla']} xla entries)"
    else:
        line += " (node default)"
    if sources:
        line += "  executables: " + ", ".join(
            f"{k} x{v}" for k, v in sorted(sources.items()))
    print(line)


def _describe_progress(j) -> None:
    """Training-plane progress: the job rollup plus one line per reporting
    replica (step, throughput, loss, workload phase, heartbeat age)."""
    p = j.status.progress
    if p is None:
        return
    now = time.time()
    stalled = f"  STALLED {p.stalled_replicas}" if p.stalled else ""
    print(f"Progress:  step={p.step}"
          + (f" (max {p.max_step}, lag {p.straggler_lag})"
             if p.straggler_lag else "")
          + f" rate={p.examples_per_sec:g} ex/s loss={p.loss:g}"
          + f" reporting={p.reporting}{stalled}")
    for r in p.replicas:
        beat = (_age(now - r.last_heartbeat) + " ago"
                if r.last_heartbeat else "never")
        mark = "  STALLED" if r.stalled else ""
        src = f" compile={r.compile_source}" if r.compile_source else ""
        res = (f" resumed@{r.resumed_from_step}"
               if r.resumed_from_step else "")
        print(f"  {r.type.value}-{r.index}: step={r.step} "
              f"rate={r.examples_per_sec:g} loss={r.loss:g} "
              f"phase={r.phase or '-'}{src}{res} beat {beat}{mark}")


def _describe_goodput(j) -> None:
    """Goodput section off status.goodput (obs/goodput.py ledger rollup):
    the headline ratio plus where the badput went, bucket by bucket —
    'where did my accelerator-hours go' without a live TSDB."""
    from ..obs.phases import GOODPUT_BUCKETS, NON_OCCUPIED_BUCKETS

    gp = j.status.goodput
    if gp is None or gp.wall_s <= 0:
        return
    print(f"Goodput:   {gp.ratio:.0%} — {gp.goodput_s}s good of "
          f"{gp.occupied_s}s occupied (wall {gp.wall_s}s)")
    badput = {b: s for b, s in sorted(gp.buckets.items())
              if b not in GOODPUT_BUCKETS and b not in NON_OCCUPIED_BUCKETS
              and s > 0}
    if badput:
        print("  Badput:  "
              + " ".join(f"{b}={s}s" for b, s in
                         sorted(badput.items(), key=lambda kv: -kv[1])))
    waiting = {b: s for b, s in sorted(gp.buckets.items())
               if b in NON_OCCUPIED_BUCKETS and s > 0}
    if waiting:
        print("  Waiting: " + " ".join(f"{b}={s}s"
                                       for b, s in sorted(waiting.items())))


def _describe_health(cluster, job, ns: str) -> None:
    """Per-replica/per-slice health (checker/health.py) from the job's
    live pods — the slice is the TPU failure domain, so a gang with any
    missing member reports Degraded as a whole."""
    from ..api.labels import LABEL_JOB_TYPE, job_selector
    from ..api.tfjob import ReplicaType
    from ..checker import check_health

    try:
        all_pods = cluster.pods.list(ns)
    except APIError:
        return  # server lost mid-describe: skip the section
    # Same selector the controller claims with (name + runtime_id): pods
    # from a deleted same-named incarnation must not pollute the report.
    want = job_selector(job.metadata.name, job.spec.runtime_id)
    by_type = {}
    for p in all_pods:
        if any(p.metadata.labels.get(k) != v for k, v in want.items()):
            continue
        try:
            typ = ReplicaType(p.metadata.labels.get(LABEL_JOB_TYPE))
        except ValueError:
            continue
        by_type.setdefault(typ, []).append(p)
    health = check_health(job, by_type)
    print(f"Health:    {health.overall.value}")
    for typ, rh in health.replicas.items():
        missing = (f", missing indices {rh.missing_indices}"
                   if rh.missing_indices else "")
        print(f"  {typ.value}: {rh.health.value} "
              f"({rh.running} running, {rh.waiting} waiting, "
              f"{rh.succeeded} succeeded, {rh.failed} failed "
              f"of {rh.desired}{missing})")


def cmd_logs(args) -> int:
    """kubectl-logs analog: a pod's combined stdout+stderr (REST mode)."""
    from ..cluster.store import NotFound

    cluster = _rest_cluster_or_die(args, probe=False)
    if cluster is None:
        return 2
    ns = args.namespace or "default"
    try:
        sys.stdout.write(cluster.pods.read_log(ns, args.name,
                                               tail_lines=args.tail))
    except NotFound as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    except APIError as e:
        print(f"error talking to API server: {e}", file=sys.stderr)
        return 2
    return 0


def _print_shard_depths(cluster, jobs, lease) -> None:
    """Per-shard queue pressure: live depth gauges when the server's
    /metrics exposes them (in-process deployments, where the controller
    shares the server registry), else the active-job distribution over
    the same hash ring the controller routes by."""
    import re

    shards = lease.spec.shards
    if shards <= 1:
        return
    depths = {}
    try:
        for line in cluster.metrics_text().splitlines():
            m = re.match(r'kctpu_ha_shard_queue_depth\{shard="(\d+)"\}\s+'
                         r'([0-9.eE+-]+)', line)
            if m:
                depths[int(m.group(1))] = int(float(m.group(2)))
    except APIError:
        pass
    if depths:
        cells = " ".join(f"{s}:{depths.get(s, 0)}" for s in range(shards))
        print(f"shards: queue depth {cells}")
        return
    active = {}
    for j in jobs:
        if j.status.phase.value in ("Succeeded", "Failed"):
            continue
        cell = _shard_cell(j, lease)
        if cell != "-":
            active[int(cell)] = active.get(int(cell), 0) + 1
    cells = " ".join(f"{s}:{active.get(s, 0)}" for s in range(shards))
    print(f"shards: active jobs {cells}")


def _print_tenant_rollup(cluster, jobs) -> None:
    """One rollup line per scheduler tenant when the cluster is actually
    multi-tenant: job count, summed training throughput, occupied-weighted
    goodput (client-side, same weighting as the kctpu_tenant_goodput_ratio
    gauge), and the scheduler's live dominant share / borrowed slices."""
    agg: dict = {}
    for j in jobs:
        t = tenant_of(j)
        row = agg.setdefault(t, {"jobs": 0, "rate": 0.0,
                                 "good": 0.0, "occ": 0.0})
        row["jobs"] += 1
        p = j.status.progress
        if p is not None:
            row["rate"] += p.examples_per_sec
        gp = j.status.goodput
        if gp is not None:
            row["good"] += gp.goodput_s
            row["occ"] += gp.occupied_s
    gauges = _tenant_gauges(cluster)
    if len(set(agg) | set(gauges)) < 2:
        return  # single-tenant: the per-job rows already tell the story
    cells = []
    for t in sorted(set(agg) | set(gauges)):
        row = agg.get(t, {"jobs": 0, "rate": 0.0, "good": 0.0, "occ": 0.0})
        cell = f"{t}:{row['jobs']}j"
        if row["rate"]:
            cell += f" {row['rate']:g}ex/s"
        if row["occ"] > 0:
            cell += f" good={row['good'] / row['occ']:.0%}"
        g = gauges.get(t)
        if g is not None:
            cell += f" share={g.get('share', 0.0):.2f}"
            if g.get("borrowed"):
                cell += f" borrowed={g['borrowed']}"
        cells.append(cell)
    print("tenants: " + "  ".join(cells))


def cmd_top(args) -> int:
    """kubectl-top analog for TFJobs: live training-plane progress, one
    row per job — step, throughput, straggler lag, stall state, heartbeat
    age.  ``-w`` re-renders every N seconds until interrupted."""
    cluster = _rest_cluster_or_die(args, probe=False)
    if cluster is None:
        return 2
    while True:
        try:
            jobs = cluster.tfjobs.list(args.namespace or None)
        except APIError as e:
            print(f"error talking to API server: {e}", file=sys.stderr)
            return 2
        now = time.time()
        lease = _fetch_lease(cluster)
        if lease is not None:
            print(_leader_line(lease))
            _print_shard_depths(cluster, jobs, lease)
        _print_tenant_rollup(cluster, jobs)
        print(f"{'NAMESPACE':<12} {'NAME':<32} {'PHASE':<10} {'STEP':<10} "
              f"{'RATE':<10} {'QPS':<8} {'TTFT':<9} {'OCC':<5} "
              f"{'GWQPS':<7} {'HIT':<5} {'GOODPUT':<8} "
              f"{'LOSS':<10} {'LAG':<6} {'STALLED':<20} "
              f"{'SHARD':<6} BEAT")
        # Stalled jobs surface first (the rows an operator is looking for),
        # then the busiest.
        def sort_key(j):
            p = j.status.progress
            return (0 if (p and p.stalled) else 1,
                    -(p.examples_per_sec if p else 0.0),
                    j.metadata.namespace, j.metadata.name)
        for j in sorted(jobs, key=sort_key):
            p = j.status.progress
            if p is None:
                step = rate = loss = lag = beat = "-"
                stalled = "-"
            else:
                step, rate = _progress_cells(j)
                loss = f"{p.loss:g}"
                lag = str(p.straggler_lag)
                stalled = ",".join(p.stalled_replicas) or "no"
                beat = (_age(now - p.last_heartbeat) if p.last_heartbeat
                        else "never")
            qps, ttft = _serving_cells(j)
            sv = j.status.serving
            occ = f"{sv.occupancy:.0%}" if sv is not None and sv.ready else "-"
            gwqps, hit = _gateway_cells(j)
            gp = j.status.goodput
            good = (f"{gp.ratio:.0%}"
                    if gp is not None and gp.occupied_s > 0 else "-")
            print(f"{j.metadata.namespace:<12} {j.metadata.name:<32} "
                  f"{j.status.phase.value:<10} {step:<10} {rate:<10} "
                  f"{qps:<8} {ttft:<9} {occ:<5} "
                  f"{gwqps:<7} {hit:<5} {good:<8} "
                  f"{loss:<10} {lag:<6} {stalled:<20} "
                  f"{_shard_cell(j, lease):<6} {beat}")
        if not args.watch:
            return 0
        try:
            time.sleep(args.watch)
        except KeyboardInterrupt:
            return 0
        print()


def cmd_goodput(args) -> int:
    """Time-accounting table off each job's status.goodput (the controller
    ledger's per-job rollup): headline ratio, goodput/occupied/wall
    seconds, dominant badput bucket — plus an occupied-weighted cluster
    rollup.  ``--job`` drills into one job's full bucket breakdown."""
    from ..obs.phases import GOODPUT_BUCKETS, NON_OCCUPIED_BUCKETS

    cluster = _rest_cluster_or_die(args, probe=False)
    if cluster is None:
        return 2
    try:
        jobs = cluster.tfjobs.list(args.namespace or None)
    except APIError as e:
        print(f"error talking to API server: {e}", file=sys.stderr)
        return 2
    if args.job:
        matches = [j for j in jobs if j.metadata.name == args.job]
        if not matches:
            print(f"tfjob {args.job} not found", file=sys.stderr)
            return 1
        j = matches[0]
        gp = j.status.goodput
        if gp is None or gp.wall_s <= 0:
            print(f"{j.metadata.namespace}/{j.metadata.name}: no goodput "
                  f"ledger yet (job too young, or controller not running)")
            return 0
        print(f"{j.metadata.namespace}/{j.metadata.name}: "
              f"goodput {gp.ratio:.0%} "
              f"({gp.goodput_s}s of {gp.occupied_s}s occupied; "
              f"wall {gp.wall_s}s)")
        print(f"{'BUCKET':<16} {'SECONDS':>8}  CLASS")
        for b, s in sorted(gp.buckets.items(), key=lambda kv: -kv[1]):
            cls = ("goodput" if b in GOODPUT_BUCKETS
                   else "waiting" if b in NON_OCCUPIED_BUCKETS
                   else "badput")
            print(f"{b:<16} {s:>8}  {cls}")
        return 0
    rows = [(j, j.status.goodput) for j in jobs
            if j.status.goodput is not None and j.status.goodput.wall_s > 0]
    if not rows:
        print("No goodput ledgers found (controller attaches status."
              "goodput once jobs have run for a few seconds).")
        return 0
    if args.tenant:
        # Per-tenant rollup: same occupied-time weighting as the
        # kctpu_tenant_goodput_ratio gauge, computed client-side off the
        # per-job status ledgers so it works against any server.
        agg: dict = {}
        for j, gp in rows:
            t = tenant_of(j)
            row = agg.setdefault(t, {"jobs": 0, "good": 0, "occ": 0,
                                     "wall": 0})
            row["jobs"] += 1
            row["good"] += gp.goodput_s
            row["occ"] += gp.occupied_s
            row["wall"] += gp.wall_s
        print(f"{'TENANT':<16} {'JOBS':>5} {'GOODPUT':<8} {'GOOD_S':>8} "
              f"{'OCC_S':>8} {'WALL_S':>8}")
        ranked = sorted(agg.items(),
                        key=lambda kv: (kv[1]["good"] / kv[1]["occ"]
                                        if kv[1]["occ"] else 1.0))
        for t, row in ranked:
            ratio = row["good"] / row["occ"] if row["occ"] else 1.0
            print(f"{t:<16} {row['jobs']:>5} {ratio:<8.0%} "
                  f"{row['good']:>8} {row['occ']:>8} {row['wall']:>8}")
        return 0
    print(f"{'NAMESPACE':<12} {'NAME':<32} {'GOODPUT':<8} {'GOOD_S':>8} "
          f"{'OCC_S':>8} {'WALL_S':>8}  TOP-BADPUT")
    tot_good = tot_occ = 0
    for j, gp in sorted(rows, key=lambda r: r[1].ratio):
        badput = {b: s for b, s in gp.buckets.items()
                  if b not in GOODPUT_BUCKETS
                  and b not in NON_OCCUPIED_BUCKETS and s > 0}
        top = (max(badput.items(), key=lambda kv: kv[1])
               if badput else None)
        top_cell = f"{top[0]}={top[1]}s" if top else "-"
        print(f"{j.metadata.namespace:<12} {j.metadata.name:<32} "
              f"{gp.ratio:<8.0%} {gp.goodput_s:>8} "
              f"{gp.occupied_s:>8} {gp.wall_s:>8}  {top_cell}")
        tot_good += gp.goodput_s
        tot_occ += gp.occupied_s
    ratio = tot_good / tot_occ if tot_occ else 1.0
    print(f"cluster: goodput {ratio:.0%} "
          f"({tot_good}s of {tot_occ}s occupied, {len(rows)} job(s))")
    return 0


def cmd_delete(args) -> int:
    """kubectl-delete analog for TFJobs (REST mode); finalizer-gated
    cleanup runs controller-side."""
    from ..cluster.store import NotFound

    cluster = _rest_cluster_or_die(args, probe=False)
    if cluster is None:
        return 2
    ns = args.namespace or "default"
    try:
        cluster.tfjobs.delete(ns, args.name)
    except NotFound:
        print(f"tfjob {ns}/{args.name} not found", file=sys.stderr)
        return 1
    except APIError as e:
        print(f"error talking to API server: {e}", file=sys.stderr)
        return 2
    print(f"tfjob \"{args.name}\" deleted")
    return 0


def cmd_metrics(args) -> int:
    """Prometheus-text metrics: scraped from the API server's GET /metrics
    in REST mode, or the local process registry otherwise (useful mostly
    right after an in-process `run` in the same interpreter)."""
    if args.kubeconfig or args.master:
        cluster = _rest_cluster_or_die(args, probe=False)
        if cluster is None:
            return 2
        try:
            sys.stdout.write(cluster.metrics_text())
        except APIError as e:
            print(f"error talking to API server: {e}", file=sys.stderr)
            return 2
        return 0
    from ..obs import REGISTRY

    sys.stdout.write(REGISTRY.render())
    return 0


def cmd_trace(args) -> int:
    """Chrome trace dump (load in chrome://tracing or ui.perfetto.dev):
    the API server's span buffer in REST mode, the local tracer otherwise.
    With ``--job J``, reconstructs the job's cross-process causal timeline
    (submit -> queued -> admitted -> kubelet start -> rendezvous -> compile
    -> first step; serving: request ingest -> queue -> prefill -> decode)
    from the span ids instead of dumping raw JSON."""
    if args.input:
        from ..obs import load_trace_events

        events = load_trace_events(args.input)
        doc = {"traceEvents": events}
    elif args.kubeconfig or args.master:
        cluster = _rest_cluster_or_die(args, probe=False)
        if cluster is None:
            return 2
        try:
            doc = cluster.trace_events()
        except APIError as e:
            print(f"error talking to API server: {e}", file=sys.stderr)
            return 2
    else:
        from ..obs import TRACER, merge_trace_dir

        trace_dir = os.environ.get("KCTPU_TRACE_DIR", "")
        if trace_dir and os.path.isdir(trace_dir):
            doc = merge_trace_dir(trace_dir, tracer=TRACER)
        else:
            doc = TRACER.chrome_trace()
    if args.job:
        return _print_causal_trace(doc.get("traceEvents", []), args.job)
    out = json.dumps(doc)
    if args.dump and args.dump != "-":
        with open(args.dump, "w") as fh:
            fh.write(out)
        print(f"wrote {len(doc.get('traceEvents', []))} spans to {args.dump}")
    else:
        sys.stdout.write(out + "\n")
    return 0


def _print_causal_trace(events, job: str) -> int:
    """Render one job's causal tree.  The trace id comes from the job's
    root span (``job/submit`` carries ``args.job``), so this needs no API
    access — any merged trace document is enough."""
    from ..obs.trace import (
        event_ids, events_for_trace, orphan_events, render_timeline)

    trace_id = ""
    for e in events:
        a = e.get("args") or {}
        if a.get("job") == job and event_ids(e)[0]:
            trace_id = event_ids(e)[0]
            break
    if not trace_id:
        print(f"no trace found for job {job!r} "
              f"(was the controller tracing this job?)", file=sys.stderr)
        return 1
    mine = events_for_trace(events, trace_id)
    orphans = orphan_events(mine)
    pids = {e.get("pid") for e in mine}
    print(f"trace {trace_id} job={job}: {len(mine)} spans across "
          f"{len(pids)} process(es), {len(orphans)} orphan(s)")
    for line in render_timeline(mine):
        print(f"  {line}")
    return 0


def cmd_query(args) -> int:
    """Windowed queries over the retained-series store: the API server's
    /debug/query in REST mode, the local process TSDB otherwise."""
    params = {"op": args.op, "name": args.name}
    if args.labels:
        # Flag form is k=v,k=v; the query surface takes a JSON object.
        try:
            pairs = dict(kv.split("=", 1) for kv in args.labels.split(","))
        except ValueError:
            print(f"error: bad --labels {args.labels!r} (want K=V,K=V)",
                  file=sys.stderr)
            return 2
        params["labels"] = json.dumps(pairs)
    if args.window:
        params["window"] = str(args.window)
    if args.q is not None:
        params["q"] = str(args.q)
    if args.kubeconfig or args.master:
        cluster = _rest_cluster_or_die(args, probe=False)
        if cluster is None:
            return 2
        try:
            doc = cluster.debug_query(params)
        except APIError as e:
            print(f"error talking to API server: {e}", file=sys.stderr)
            return 2
    else:
        from ..obs.tsdb import default_tsdb

        doc = default_tsdb().query(params)
    print(json.dumps(doc, indent=1, sort_keys=True))
    return 1 if doc.get("error") else 0


def cmd_alerts(args) -> int:
    """SLO burn-rate alert states (obs/slo.py): the API server's
    /debug/slos in REST mode, the local engine otherwise."""
    if args.kubeconfig or args.master:
        cluster = _rest_cluster_or_die(args, probe=False)
        if cluster is None:
            return 2
        try:
            doc = cluster.debug_slos()
        except APIError as e:
            print(f"error talking to API server: {e}", file=sys.stderr)
            return 2
    else:
        from ..obs.slo import default_slo_engine

        doc = default_slo_engine().state()
    alerts = doc.get("alerts", [])
    if not args.all:
        alerts = [a for a in alerts if a.get("active")]
    if not alerts:
        n = len(doc.get("objectives", []))
        print(f"no firing alerts ({n} objective(s) evaluated; "
              f"--all shows quiet ones)")
        return 0
    print(f"{'SLO':<20} {'SERIES':<36} {'STATE':<9} {'VALUE':<12} "
          f"{'BURN(fast/slow)':<16} SINCE")
    now = time.time()
    for a in alerts:
        labels = a.get("labels") or {}
        series = (",".join(f"{k}={v}" for k, v in sorted(labels.items()))
                  or "_cluster")
        state = "FIRING" if a.get("active") else "ok"
        since = _age(max(0.0, now - a["since"])) if a.get("since") else "-"
        print(f"{a['slo']:<20} {series:<36} {state:<9} "
              f"{a.get('value', 0):<12g} "
              f"{a.get('burn_fast', 0):g}/{a.get('burn_slow', 0):<10g} "
              f"{since}")
    return 0


def cmd_debug(args) -> int:
    """Flight-recorder surface.  ``debug dump JOB`` captures a postmortem
    bundle for a live job by assembling the same artefacts the controller
    captures on terminal failure — job status + events + pod progress over
    REST, plus the reachable trace spans — into $KCTPU_DEBUG_DIR."""
    from ..cluster.store import NotFound
    from ..obs import flight

    if args.debug_cmd != "dump":
        print("usage: kctpu debug dump JOB [-n NS] [--out DIR]",
              file=sys.stderr)
        return 2
    out_dir = args.out or flight.debug_dir()
    if not out_dir:
        print("error: set $KCTPU_DEBUG_DIR or pass --out DIR",
              file=sys.stderr)
        return 2
    cluster = _rest_cluster_or_die(args, probe=False)
    if cluster is None:
        return 2
    ns = args.namespace or "default"
    try:
        j = cluster.tfjobs.get(ns, args.name)
    except NotFound:
        print(f"tfjob {ns}/{args.name} not found", file=sys.stderr)
        return 1
    except APIError as e:
        print(f"error talking to API server: {e}", file=sys.stderr)
        return 2
    from ..api.labels import ANNOTATION_TRACE_CONTEXT
    from ..obs.trace import TraceContext
    from ..utils import serde

    ctx = TraceContext.decode(
        j.metadata.annotations.get(ANNOTATION_TRACE_CONTEXT, ""))
    if ctx is None and j.metadata.uid:
        ctx = TraceContext.for_job(j.metadata.uid)
    events = []
    try:
        for ev in cluster.events.list(ns):
            if ev.involved_object.name == args.name:
                events.append({
                    "type": ev.type, "reason": ev.reason,
                    "message": ev.message, "count": ev.count,
                    "timestamp": ev.last_timestamp,
                    "firstTimestamp": ev.first_timestamp})
    except APIError:
        pass
    progress = {}
    try:
        for p in cluster.pods.list(ns):
            ref = p.metadata.owner_references
            if (p.status.progress is not None and ref
                    and ref[0].name == args.name):
                progress[p.metadata.name] = serde.to_dict(p.status.progress)
    except APIError:
        pass
    # The API server's span buffer holds what the controller and kubelets
    # emitted; local spans + $KCTPU_TRACE_DIR are folded in by record_flight.
    server_spans = []
    try:
        server_spans = cluster.trace_events().get("traceEvents", [])
    except APIError:
        pass
    path = flight.record_flight(
        ns, args.name, reason="OnDemand",
        trace_id=ctx.trace_id if ctx else "",
        events=events, progress=progress,
        status=serde.to_dict(j.status),
        extra_trace_events=server_spans,
        out_dir=out_dir)
    if path is None:
        print("error: could not write the bundle", file=sys.stderr)
        return 1
    bundle = flight.read_bundle(path)
    manifest = bundle.get("manifest.json", {})
    print(f"wrote {path}")
    print(f"  trace spans: {manifest.get('trace_spans', 0)}  "
          f"events: {manifest.get('events', 0)}  "
          f"progress pods: {len(progress)}")
    return 0


def cmd_run(args) -> int:
    logging.basicConfig(
        level=logging.DEBUG if args.v >= 4 else logging.INFO,
        format="%(asctime)s %(levelname).1s %(name)s: %(message)s",
    )
    use_rest = bool(args.kubeconfig or args.master)
    if not args.in_memory and not use_rest:
        print("error: pass --in-memory, or -kubeconfig/-master for an API "
              "server (see the `serve` subcommand)", file=sys.stderr)
        return 2

    stop = setup_signal_handler()
    trace_dir = ""
    if args.trace_out:
        # Executed pods inherit this via the kubelet's env merge and dump
        # their spans here; merged with the controller's own spans at exit.
        import tempfile

        trace_dir = tempfile.mkdtemp(prefix="kctpu-trace-")
        os.environ["KCTPU_TRACE_DIR"] = trace_dir
    kubelet = None
    if use_rest:
        # Real-cluster mode: BuildConfigFromFlags parity
        # (ref: cmd/controller/main.go:47-60).  The API server owns the
        # kubelet/inventory; this process is only the controller.
        cluster = _rest_cluster_or_die(args)
        if cluster is None:
            return 2
        inventory = None
    else:
        cluster = Cluster()
        inventory, kubelet = _build_substrate(args, cluster)
    lease_mgr = None
    if args.leader_elect:
        # HA mode (docs/HA.md): acquire the leader lease before starting
        # the controller; every write carries the lease generation as its
        # fencing token, so if this process is ever deposed its in-flight
        # writes are rejected server-side.
        import socket

        from ..ha.lease import LeaseManager

        identity = f"{socket.gethostname()}-{os.getpid()}"
        lease_mgr = LeaseManager(cluster.leases, identity,
                                 duration_s=args.lease_duration,
                                 shards=max(1, args.controller_shards))
        cluster.set_fence_provider(lease_mgr.token)
        lease_mgr.start()
        logger.info("leader election: candidate %s waiting for the lease",
                    identity)
        while not lease_mgr.is_leader and not stop.is_set():
            time.sleep(0.05)
        if stop.is_set():
            lease_mgr.stop()
            return 0
        logger.info("leader election: %s elected (generation %d)",
                    identity, lease_mgr.generation)
    ctrl = Controller(cluster, inventory=inventory,
                      resync_period_s=args.resync_period,
                      manage_workers=args.manage_workers,
                      controller_shards=max(1, args.controller_shards))
    if args.obs:
        # Retained-series sampling + SLO burn-rate evaluation
        # (docs/OBSERVABILITY.md); alerts land in the event stream and
        # `kctpu alerts` / the `kctpu get` banner.
        ctrl.start_obs_plane(interval_s=args.obs_interval)
    if kubelet is not None:
        kubelet.start()
    ctrl.run(threadiness=args.threadiness)
    logger.info("tfjob-controller %s (git %s) started: %d workers, %.0fs resync",
                __version__, GIT_SHA, args.threadiness, args.resync_period)

    terminal = (TFJobPhase.SUCCEEDED, TFJobPhase.FAILED)
    jobs = []
    try:
        try:
            jobs = load_manifests(args.manifests) if args.manifests else []
        except (OSError, yaml.YAMLError, ValueError, TypeError) as e:
            print(f"error loading manifests: {e}", file=sys.stderr)
            return 1
        for job in jobs:
            created = cluster.tfjobs.create(job)
            logger.info("applied TFJob %s/%s", created.metadata.namespace or "default",
                        created.metadata.name)
        while not stop.is_set():
            time.sleep(0.2)
            if args.until_done and jobs:
                all_jobs = cluster.tfjobs.list()
                if all_jobs and all(j.status.phase in terminal for j in all_jobs):
                    break
    except APIError as e:
        # Mid-run API server loss (REST mode): fail cleanly, not a traceback.
        print(f"error talking to API server: {e}", file=sys.stderr)
        return 2
    finally:
        ctrl.stop()
        if lease_mgr is not None:
            lease_mgr.stop(release=True)
        if kubelet is not None:
            kubelet.stop()
        if args.trace_out:
            from ..obs import TRACER, merge_trace_dir

            doc = merge_trace_dir(trace_dir, tracer=TRACER)
            with open(args.trace_out, "w") as fh:
                json.dump(doc, fh)
            print(f"trace: {len(doc['traceEvents'])} spans -> {args.trace_out}")

    rc = 0
    try:
        final_jobs = cluster.tfjobs.list()
    except APIError as e:
        print(f"error talking to API server: {e}", file=sys.stderr)
        return 2
    for j in final_jobs:
        key = f"{j.metadata.namespace}/{j.metadata.name}"
        print(f"{key}: phase={j.status.phase.value}")
        for rs in j.status.tf_replica_statuses:
            hist = {k.value: v for k, v in rs.tf_replicas_states.items()}
            print(f"  {rs.type.value}: state={rs.state.value} pods={len(rs.pod_names)} {hist}")
        if args.events:
            for e in ctrl.recorder.events_for(j.metadata.namespace, j.metadata.name):
                print(f"  event {e.type} {e.reason}: {e.message} (x{e.count})")
        if j.status.phase == TFJobPhase.FAILED:
            rc = 3
    snap = ctrl.metrics.snapshot()
    print(f"metrics: syncs={snap['syncs']} errors={snap['sync_errors']} "
          f"creates={snap['creates']} deletes={snap['deletes']} "
          f"reconcile_p50={snap['reconcile_p50_s'] * 1e3:.2f}ms "
          f"p99={snap['reconcile_p99_s'] * 1e3:.2f}ms")
    return rc


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="tfjob-controller",
                                description="TPU-native TFJob controller")
    p.add_argument("-version", "--version", action="store_true",
                   help="print version and exit (ref flag parity)")
    p.add_argument("-kubeconfig", "--kubeconfig", default="",
                   help="path to a kubeconfig; selects the REST transport "
                        "(ref flag parity: cmd/controller/main.go:47-60)")
    p.add_argument("-master", "--master", default="",
                   help="API server address; overrides the kubeconfig server")
    sub = p.add_subparsers(dest="cmd")

    sub.add_parser("version", help="print version and exit")

    s = sub.add_parser("serve", help="run the in-memory API server + kubelet "
                                     "as a standalone process")
    s.add_argument("--port", type=int, default=0,
                   help="listen port (default: ephemeral, printed at startup)")
    s.add_argument("--wal-dir", default="", metavar="DIR",
                   help="durable mode: journal every write to a WAL in DIR "
                        "and recover WAL-over-snapshot at startup, so a "
                        "restarted server is RV-identical (docs/HA.md)")
    s.add_argument("--token", default="", help="require this bearer token")
    s.add_argument("--execute", action="store_true",
                   help="kubelet executes container commands as local processes")
    s.add_argument("--sim-run-seconds", type=float, default=0.05)
    s.add_argument("--tpu-slices", type=int, default=1)
    s.add_argument("--tpu-slice-type", default="v5e-8")
    s.add_argument("--tpu-slice-hosts", type=int, default=2)
    s.add_argument("--no-sched", action="store_true",
                   help="first-come gang admission (no priority queue/"
                        "preemption/backfill) — the scheduler baseline")
    s.add_argument("--no-preemption", action="store_true",
                   help="keep the priority queue but never evict running gangs")
    s.add_argument("-v", type=int, default=0)

    v = sub.add_parser("validate", help="validate TFJob manifests")
    v.add_argument("-f", "--files", nargs="+", required=True)

    g = sub.add_parser("get", help="list TFJobs (REST mode: pass -master)")
    g.add_argument("-n", "--namespace", default="",
                   help="namespace filter (default: all)")
    g.add_argument("--tenant", default="", metavar="T",
                   help="only jobs whose resolved tenant (tenant label, "
                        "else namespace) is T")

    d = sub.add_parser("describe", help="describe one TFJob + its events "
                                        "(REST mode: pass -master)")
    d.add_argument("name")
    d.add_argument("-n", "--namespace", default="default")

    lg = sub.add_parser("logs", help="print a pod's combined stdout+stderr "
                                     "(REST mode: pass -master)")
    lg.add_argument("name")
    lg.add_argument("-n", "--namespace", default="default")
    lg.add_argument("--tail", type=int, default=0, metavar="N",
                    help="only the last N lines (kubelet tail-reads files "
                         "instead of shipping whole logs)")

    tp = sub.add_parser("top", help="live training-plane progress per TFJob "
                                    "(REST mode: pass -master)")
    tp.add_argument("-n", "--namespace", default="",
                    help="namespace filter (default: all)")
    tp.add_argument("-w", "--watch", type=float, default=0.0, metavar="S",
                    help="re-render every S seconds until interrupted")

    gp = sub.add_parser("goodput", help="phase-attributed time accounting "
                                        "per TFJob + cluster rollup "
                                        "(obs/goodput.py ledger)")
    gp.add_argument("-n", "--namespace", default="")
    gp.add_argument("--job", default="", metavar="NAME",
                    help="per-bucket breakdown for one job instead of the "
                         "fleet table")
    gp.add_argument("--tenant", action="store_true",
                    help="aggregate the fleet table per tenant "
                         "(occupied-weighted, the gauge's weighting)")

    de = sub.add_parser("delete", help="delete a TFJob (REST mode: pass -master)")
    de.add_argument("name")
    de.add_argument("-n", "--namespace", default="default")

    sub.add_parser("metrics", help="print Prometheus-text metrics "
                                   "(REST mode scrapes the server's /metrics)")

    tr = sub.add_parser("trace", help="dump recorded spans as Chrome trace "
                                      "JSON (REST mode reads /debug/traces); "
                                      "--job renders one causal timeline")
    tr.add_argument("--dump", default="-", metavar="PATH",
                    help="output file (default: stdout)")
    tr.add_argument("--job", default="", metavar="NAME",
                    help="reconstruct NAME's cross-process causal timeline "
                         "(submit -> queue -> admit -> kubelet -> first step) "
                         "instead of dumping raw JSON")
    tr.add_argument("--input", default="", metavar="FILE",
                    help="read spans from a merged trace file (e.g. the one "
                         "`run --trace-out` wrote) instead of a live source")

    q = sub.add_parser("query", help="windowed query over the retained-"
                                     "series store (REST mode reads "
                                     "/debug/query; docs/OBSERVABILITY.md)")
    q.add_argument("name", nargs="?", default="",
                   help="metric name, e.g. kctpu_tfjobs (not needed for "
                        "--op series)")
    q.add_argument("--op", default="range",
                   choices=["latest", "range", "rate", "avg_over_time",
                            "quantile", "series"],
                   help="query operator (default: range)")
    q.add_argument("--labels", default="", metavar="K=V,K=V",
                   help="label matchers, comma-separated")
    q.add_argument("--window", type=float, default=0.0, metavar="S",
                   help="lookback window in seconds (default: raw retention)")
    q.add_argument("--q", type=float, default=None, metavar="Q",
                   help="quantile in [0,1] for --op quantile")

    al = sub.add_parser("alerts", help="SLO burn-rate alert states "
                                       "(REST mode reads /debug/slos)")
    al.add_argument("--all", action="store_true",
                    help="include quiet objectives, not just firing alerts")

    db = sub.add_parser("debug", help="flight-recorder surface")
    dbs = db.add_subparsers(dest="debug_cmd")
    dd = dbs.add_parser("dump", help="capture a postmortem bundle for a "
                                     "live job into $KCTPU_DEBUG_DIR "
                                     "(REST mode: pass -master)")
    dd.add_argument("name")
    dd.add_argument("-n", "--namespace", default="default")
    dd.add_argument("--out", default="", metavar="DIR",
                    help="bundle root (default: $KCTPU_DEBUG_DIR)")

    vt = sub.add_parser(
        "vet", add_help=False,
        help="AST-lint the project's codified concurrency/controller "
             "invariants incl. the static lock graph (docs/ANALYSIS.md); "
             "args pass through (--json for machine-readable findings)")
    vt.add_argument("vet_args", nargs=argparse.REMAINDER)

    ck = sub.add_parser(
        "check", add_help=False,
        help="model-check the store/watch plane: linearizability + "
             "watch-delivery exactness under seeded deterministic "
             "simulation (docs/ANALYSIS.md); args pass through "
             "(--self-test, --seeds, --json)")
    ck.add_argument("check_args", nargs=argparse.REMAINDER)

    r = sub.add_parser("run", help="run the controller")
    r.add_argument("--in-memory", action="store_true",
                   help="run against the in-memory cluster substrate")
    r.add_argument("--manifests", nargs="*", default=[],
                   help="TFJob manifest files/dirs to apply at startup")
    r.add_argument("--execute", action="store_true",
                   help="kubelet executes container commands as local processes")
    r.add_argument("--until-done", action="store_true",
                   help="exit once every applied job reaches a terminal phase")
    r.add_argument("--events", action="store_true", help="print per-job events at exit")
    r.add_argument("--trace-out", default="", metavar="PATH",
                   help="write a merged Chrome trace (controller + executed "
                        "pods) to PATH at exit")
    r.add_argument("--obs", action="store_true",
                   help="start the obs plane: retained-series sampling "
                        "(kctpu query) + SLO burn-rate alerting "
                        "(kctpu alerts; docs/OBSERVABILITY.md)")
    r.add_argument("--obs-interval", type=float, default=1.0, metavar="S",
                   help="TSDB sampling cadence when --obs is on")
    r.add_argument("--threadiness", type=int, default=2, help="sync workers (ref: 2)")
    r.add_argument("--controller-shards", type=int, default=1, metavar="N",
                   help="consistent-hash shard workers over job UIDs "
                        "(each gets --threadiness sync workers; "
                        "docs/HA.md)")
    r.add_argument("--leader-elect", action="store_true",
                   help="acquire the leader lease before starting (fast "
                        "failover; writes carry the fencing token)")
    r.add_argument("--lease-duration", type=float, default=2.0, metavar="S",
                   help="leader lease duration (renewed at S/4)")
    r.add_argument("--manage-workers", type=int, default=8,
                   help="max concurrent child create/delete calls per "
                        "controller (slow-start batched; 1 = serial plan "
                        "execution)")
    r.add_argument("--resync-period", type=float, default=30.0, help="informer resync (ref: 30s)")
    r.add_argument("--sim-run-seconds", type=float, default=0.05,
                   help="simulated pod run time when not using --execute")
    r.add_argument("--tpu-slices", type=int, default=1, help="fake TPU slices in inventory")
    r.add_argument("--tpu-slice-type", default="v5e-8")
    r.add_argument("--tpu-slice-hosts", type=int, default=2)
    r.add_argument("--no-sched", action="store_true",
                   help="first-come gang admission (no priority queue/"
                        "preemption/backfill) — the scheduler baseline")
    r.add_argument("--no-preemption", action="store_true",
                   help="keep the priority queue but never evict running gangs")
    r.add_argument("-v", type=int, default=0, help="log verbosity (glog parity)")
    return p


def main(argv=None) -> int:
    try:
        return _main(argv)
    except BrokenPipeError:
        # Downstream pager/head closed the pipe: exit quietly like any
        # well-behaved CLI (BSD-style 141 would also do; 0 keeps scripts calm).
        try:
            sys.stdout.close()
        except OSError:
            pass
        return 0


def _main(argv=None) -> int:
    raw = list(sys.argv[1:] if argv is None else argv)
    if raw[:1] == ["vet"]:
        # Route ahead of argparse: REMAINDER does not reliably capture
        # leading optionals (bpo-17050), so `kctpu vet --root X` would die
        # in the parent parser.  The subparser stays for help listing.
        from ..analysis import vet

        return vet.main(raw[1:])
    if raw[:1] == ["check"]:
        # Same early routing as vet, same bpo-17050 reason.
        from ..analysis import simcheck

        return simcheck.main(raw[1:])
    args = build_parser().parse_args(argv)
    if args.version or args.cmd == "version":
        return cmd_version(args)
    if args.cmd == "validate":
        return cmd_validate(args)
    if args.cmd == "serve":
        return cmd_serve(args)
    if args.cmd == "get":
        return cmd_get(args)
    if args.cmd == "describe":
        return cmd_describe(args)
    if args.cmd == "logs":
        return cmd_logs(args)
    if args.cmd == "top":
        return cmd_top(args)
    if args.cmd == "goodput":
        return cmd_goodput(args)
    if args.cmd == "delete":
        return cmd_delete(args)
    if args.cmd == "metrics":
        return cmd_metrics(args)
    if args.cmd == "trace":
        return cmd_trace(args)
    if args.cmd == "query":
        return cmd_query(args)
    if args.cmd == "alerts":
        return cmd_alerts(args)
    if args.cmd == "debug":
        return cmd_debug(args)
    if args.cmd == "vet":
        from ..analysis import vet

        return vet.main(args.vet_args)
    if args.cmd == "check":
        from ..analysis import simcheck

        return simcheck.main(args.check_args)
    if args.cmd == "run":
        return cmd_run(args)
    build_parser().print_help()
    return 0
