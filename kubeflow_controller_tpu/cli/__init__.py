"""Process shell (ref: cmd/controller/main.go)."""

from .main import main  # noqa: F401
