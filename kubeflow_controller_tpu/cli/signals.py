"""Signal handling (ref: pkg/util/signals/signals.go:29-43): the first
SIGINT/SIGTERM requests a graceful stop; the second exits immediately."""

from __future__ import annotations

import signal
import sys
import threading


def setup_signal_handler() -> threading.Event:
    stop = threading.Event()
    state = {"hits": 0}

    def handler(signum, frame):
        state["hits"] += 1
        if state["hits"] == 1:
            stop.set()
        else:
            sys.exit(1)

    for sig in (signal.SIGINT, signal.SIGTERM):
        signal.signal(sig, handler)
    return stop
