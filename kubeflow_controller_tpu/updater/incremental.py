"""Incremental status rollup: skip ``compute_status`` when its inputs
didn't change.

``compute_status`` is a pure function of (job, observed pods, recovery
verdicts): deep-copy the old status, rebuild every per-type rollup, run the
health checker, aggregate progress, recompute conditions, then ``to_dict``
twice for ``should_update``.  At ``--scale 200`` that is noise; at 10k jobs
it dominates the sync — and the overwhelming majority of syncs at scale are
level-triggered re-passes (resyncs, requeues, sibling-event dedup
collapses) over a world that did not move.

The cache keys each job's last rollup by the **resourceVersions of every
input**: the job's own RV plus each observed pod's ``(name, rv)``, plus the
recovery verdicts the rollup consumes (per-type restart totals and
exhausted index sets).  Any store write to any input bumps an RV and
misses the cache; a hit PROVES the recompute would reproduce the cached
result bit-identically — which the equivalence tests assert over the
existing corpus (tests/test_scale_hotpaths.py).

Two deliberate exclusions keep the proof honest:

- **Progress-bearing jobs are never cached.**  Stall detection is a
  function of *wall-clock silence* — the exact situation where no RV
  changes — so a cached verdict could mask a stall until eviction.  Jobs
  whose pods publish heartbeats churn pod RVs every beat anyway (each beat
  is an ``update_progress`` write), so the cache would thrash for them;
  declining to cache costs nothing and keeps ``StallTracker.observe``
  running on every sync, exactly as before.
- **A hit implies "no status write needed."**  The previous miss already
  computed the status and (if it differed) wrote it — and that write
  bumped the job RV, which would have missed the cache.  So a hit means
  the stored status equals the rollup, and the controller skips
  ``should_update``'s double ``to_dict`` too.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ..api.core import Pod
from ..api.tfjob import ReplicaType, TFJob, TFJobStatus
from ..utils import locks


class RollupCache:
    """Per-job memo of the last computed ``TFJobStatus``, keyed by the
    fingerprint of every rollup input.  Thread-safe (sync workers of
    different shards may roll up concurrently); bounded by ``max_jobs``
    with oldest-inserted eviction as a leak backstop — the real lifecycle
    is :meth:`forget` on job deletion."""

    def __init__(self, max_jobs: int = 32768):
        self._lock = locks.named_lock("updater.rollup-cache")
        self._max = max_jobs
        # key -> (fingerprint, status); dict order = insertion order.
        self._entries: Dict[str, Tuple[tuple, TFJobStatus]] = {}
        self.hits = 0
        self.misses = 0

    @staticmethod
    def fingerprint(
        job: TFJob,
        pods_by_type: Dict[ReplicaType, List[Pod]],
        recovery=None,
    ) -> Optional[tuple]:
        """The rollup's input identity, or None when this job is not
        cacheable (a pod reports progress: see the module docstring)."""
        pods_fp: List[tuple] = []
        for typ in sorted(pods_by_type, key=lambda t: t.value):
            for p in pods_by_type[typ]:
                if p.status.progress is not None:
                    return None
                pods_fp.append((typ.value, p.metadata.name,
                                p.metadata.resource_version))
        rec_fp: tuple = ()
        if recovery is not None:
            rec_fp = tuple(
                (s.tf_replica_type.value,
                 recovery.restarts_for(s.tf_replica_type),
                 tuple(sorted(recovery.exhausted(s.tf_replica_type))))
                for s in job.spec.tf_replica_specs)
        return (job.metadata.resource_version, tuple(pods_fp), rec_fp)

    def lookup(self, key: str, fp: Optional[tuple]) -> Optional[TFJobStatus]:
        """The cached status for an unchanged input set, else None.  The
        returned object is the cached instance itself: rollup consumers
        treat a computed status as read-only after publication, and on a
        hit nothing downstream writes it (no change → no write)."""
        if fp is None:
            return None
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None and entry[0] == fp:
                self.hits += 1
                return entry[1]
            self.misses += 1
            return None

    def store(self, key: str, fp: Optional[tuple],
              status: TFJobStatus) -> None:
        if fp is None:
            return
        with self._lock:
            if key not in self._entries and len(self._entries) >= self._max:
                # Leak backstop: evict the oldest-inserted entry.
                self._entries.pop(next(iter(self._entries)))
            self._entries[key] = (fp, status)

    def forget(self, key: str) -> None:
        with self._lock:
            self._entries.pop(key, None)

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)
