"""Status computation: observed pods -> TFJobStatus.

Successor of pkg/controller/updater/ (local.go, distributed.go, util.go),
with the declared-but-dead status surface populated (SURVEY.md §7 step 5):

- per-type replica status: the ``TFReplicasStates`` histogram (ref:
  util.go:28-61) **plus** ``State`` and ``PodNames``, which upstream never
  fills (types.go:163-171);
- conditions Scheduled/Ready/Recovering/Recycling, which upstream declares
  and never sets (types.go:154-161; TODOs at local.go:56-57,
  distributed.go:52-53);
- ``Failed`` phase, which upstream declares and never sets (types.go:129-132):
  a replica whose pod fails under restartPolicy=Never is terminal;
- chief termination policy (types.go:81-89, unimplemented upstream):
  when a chief is named, its success/failure decides the job, replacing the
  hardcoded "all workers succeeded" rule (distributed.go:51-55);
- proper change detection via semantic comparison, instead of rebuilding
  status every sync because "deep-equal is missing" (local.go:65-79).

``compute_status`` is a pure function (job + observed pods in, fresh status
out) so it unit-tests exactly like the reference's updaters (SURVEY.md §4).
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

from ..api.core import (
    PHASE_FAILED,
    PHASE_PENDING,
    PHASE_RUNNING,
    PHASE_SUCCEEDED,
    Pod,
    is_pod_active,
)
from ..api.tfjob import (
    ChiefSpec,
    JobProgress,
    ReplicaProgress,
    ReplicaType,
    TFJob,
    TFJobCondition,
    TFJobConditionType,
    TFJobPhase,
    TFJobStatus,
    TFReplicaState,
    TFReplicaStatus,
)
from ..obs.phases import (
    POD_REASON_PREEMPTED_PREFIX,
    POD_REASON_QUEUED_PREFIX,
)
from ..planner.materialize import gang_width, pod_index, pods_by_index, spec_width
from ..utils import serde

_POD_TO_REPLICA_STATE = {
    PHASE_PENDING: TFReplicaState.WAITING,
    PHASE_RUNNING: TFReplicaState.RUNNING,
    PHASE_SUCCEEDED: TFReplicaState.SUCCEEDED,
    PHASE_FAILED: TFReplicaState.FAILED,
}


def _replica_state(pod: Pod) -> TFReplicaState:
    return _POD_TO_REPLICA_STATE.get(pod.status.phase, TFReplicaState.UNKNOWN)


def _aggregate_state(states: List[TFReplicaState], desired: int) -> TFReplicaState:
    """One state summarizing a replica set: Failed dominates, then Running,
    Waiting, Succeeded (all done), Unknown."""
    if TFReplicaState.FAILED in states:
        return TFReplicaState.FAILED
    if TFReplicaState.RUNNING in states:
        return TFReplicaState.RUNNING
    if TFReplicaState.WAITING in states or len(states) < desired:
        return TFReplicaState.WAITING
    if states and all(s == TFReplicaState.SUCCEEDED for s in states):
        return TFReplicaState.SUCCEEDED
    return TFReplicaState.UNKNOWN


def set_condition(
    status: TFJobStatus,
    ctype: TFJobConditionType,
    value: bool,
    reason: str = "",
    message: str = "",
    now: Optional[float] = None,
) -> None:
    sval = "True" if value else "False"
    for c in status.conditions:
        if c.type == ctype:
            if c.status != sval:
                c.status = sval
                c.last_transition_time = now if now is not None else time.time()
            c.reason = reason
            c.message = message
            return
    status.conditions.append(
        TFJobCondition(
            type=ctype, status=sval, reason=reason, message=message,
            last_transition_time=now if now is not None else time.time(),
        )
    )


def _find_chief(job: TFJob) -> Optional[ChiefSpec]:
    for s in job.spec.tf_replica_specs:
        if s.termination_policy and s.termination_policy.chief:
            return s.termination_policy.chief
    return None


def compute_progress(
    job: TFJob,
    pods_by_type: Dict[ReplicaType, List[Pod]],
    stalled_by_type: Optional[Dict[ReplicaType, List[int]]] = None,
) -> Optional[JobProgress]:
    """Aggregate per-pod heartbeats into job-level progress.

    ``step`` is the MIN across reporting replicas — under synchronous
    collectives the job advances only as fast as its slowest member — and
    ``straggler_lag`` (max-min) is the health signal the READY condition
    carries.  Returns None when no pod has ever reported (the pre-progress
    status shape, so legacy jobs serialize unchanged)."""
    stalled_by_type = stalled_by_type or {}
    replicas: List[ReplicaProgress] = []
    for spec in job.spec.tf_replica_specs:
        typ = spec.tf_replica_type
        stalled_idx = set(stalled_by_type.get(typ, ()))
        for p in pods_by_type.get(typ, []):
            pr = p.status.progress
            if pr is None:
                continue
            idx = pod_index(p)
            replicas.append(ReplicaProgress(
                type=typ,
                index=idx if idx is not None else -1,
                step=pr.step,
                examples_per_sec=pr.examples_per_sec,
                loss=pr.loss,
                phase=pr.phase,
                compile_source=pr.compile_source,
                resumed_from_step=pr.resumed_from_step,
                last_heartbeat=pr.timestamp,
                stalled=idx in stalled_idx,
            ))
    if not replicas:
        return None
    replicas.sort(key=lambda r: (r.type.value, r.index))
    steps = [r.step for r in replicas]
    losses = [r.loss for r in replicas if r.loss]
    return JobProgress(
        step=min(steps),
        max_step=max(steps),
        straggler_lag=max(steps) - min(steps),
        examples_per_sec=round(sum(r.examples_per_sec for r in replicas), 3),
        loss=round(sum(losses) / len(losses), 6) if losses else 0.0,
        reporting=len(replicas),
        stalled_replicas=[f"{r.type.value}-{r.index}"
                          for r in replicas if r.stalled],
        last_heartbeat=max(r.last_heartbeat for r in replicas),
        replicas=replicas,
    )


def compute_serving(
    job: TFJob,
    pods_by_type: Dict[ReplicaType, List[Pod]],
):
    """Serving-plane rollup from the Serving replicas' beats (None on
    non-serving jobs): current scale target, ready count, summed qps and
    queue depth, the WORST replica's windowed TTFT/ITL p50 (the operator
    cares about the slowest replica, not a flattering mean), mean batch
    occupancy, and the autoscale bounds for `kctpu describe`."""
    from ..api.tfjob import ServingStatus, serving_spec
    from ..serving.autoscale import serving_width

    spec = serving_spec(job)
    if spec is None:
        return None
    pods = pods_by_type.get(ReplicaType.SERVING, [])
    beats = [p.status.progress for p in pods
             if p.status.phase == PHASE_RUNNING
             and p.status.progress is not None
             and p.status.progress.phase == "serving"]
    a = job.spec.autoscale
    st = ServingStatus(
        replicas=serving_width(job),
        ready=len(beats),
        min_replicas=a.min_replicas if a else 0,
        max_replicas=a.max_replicas if a else 0,
        target_queue_depth=a.target_queue_depth if a else 0.0,
    )
    if beats:
        st.qps = round(sum(b.qps for b in beats), 3)
        st.ttft_ms = round(max(b.ttft_ms for b in beats), 3)
        st.ttft_p99_ms = round(max(b.ttft_p99_ms for b in beats), 3)
        st.itl_ms = round(max(b.itl_ms for b in beats), 3)
        st.queue_depth = sum(b.queue_depth for b in beats)
        occ = [b.slots_used / b.slots_total for b in beats if b.slots_total]
        st.occupancy = round(sum(occ) / len(occ), 4) if occ else 0.0
    return st


def compute_status(
    job: TFJob,
    pods_by_type: Dict[ReplicaType, List[Pod]],
    now: Optional[float] = None,
    tracker=None,
    recovery=None,
) -> TFJobStatus:
    """``recovery`` (optional) is the RestartTracker's RecoveryAssessment:
    it supplies the per-type restart totals (TFReplicaStatus.restarts, the
    CLI RESTARTS column) and the backoff-limit verdicts — an index whose
    restart budget is exhausted is terminal exactly like restartPolicy
    Never, with the job's reason naming the policy that gave up."""
    status = serde.deep_copy(job.status)
    prev_phase = status.phase

    # -- per-type rollups (replaces updater/util.go:28-61) --
    status.tf_replica_statuses = []
    index_done: Dict[ReplicaType, Dict[int, str]] = {}
    any_running = False
    any_terminal_failure = False
    recovering = False
    scheduled = True
    ready = True

    # Capacity-plane state carried on pod status (the scheduler's channel
    # to a controller in any process): a Pending TPU pod whose reason is
    # "GangQueued: …" is waiting in the slice queue; a Failed pod whose
    # reason is "Preempted: …" was evicted by a higher-priority gang.
    gang_queue_msg = ""
    gang_preempt_msg = ""
    # Recovery-plane terminal verdicts ("BackoffLimitExceeded: …" /
    # "RestartPolicyNever: …") — the first one becomes the Failed reason.
    terminal_msgs: List[str] = []

    for spec in job.spec.tf_replica_specs:
        typ = spec.tf_replica_type
        # Elastic gangs roll up against their CURRENT width: a degraded
        # gang with every current member Running is Scheduled/Ready (the
        # reduced width itself surfaces as the Degraded condition below).
        desired = gang_width(job, spec)
        pods = pods_by_type.get(typ, [])
        restart = spec.template.spec.restart_policy if spec.template else "OnFailure"
        replace_on_failure = restart in ("OnFailure", "Always")

        if typ in (ReplicaType.TPU, ReplicaType.SERVING):
            for p in pods:
                r = p.status.reason or ""
                if (p.status.phase == PHASE_PENDING
                        and r.startswith(POD_REASON_QUEUED_PREFIX)):
                    gang_queue_msg = r
                elif (p.status.phase == PHASE_FAILED
                        and r.startswith(POD_REASON_PREEMPTED_PREFIX)):
                    gang_preempt_msg = r

        hist: Dict[TFReplicaState, int] = {}
        states: List[TFReplicaState] = []
        for p in pods:
            st = _replica_state(p)
            states.append(st)
            hist[st] = hist.get(st, 0) + 1
            if st == TFReplicaState.RUNNING:
                any_running = True

        exhausted = recovery.exhausted(typ) if recovery is not None else set()

        by_idx = pods_by_index(pods)
        done: Dict[int, str] = {}
        for i in range(desired):
            plist = by_idx.get(i, [])
            if any(p.status.phase == PHASE_SUCCEEDED for p in plist):
                done[i] = PHASE_SUCCEEDED
            failed = [p for p in plist if p.status.phase == PHASE_FAILED]
            has_active = any(is_pod_active(p) for p in plist)
            if failed and not replace_on_failure and not has_active and i not in done:
                done[i] = PHASE_FAILED
                any_terminal_failure = True
                terminal_msgs.append(
                    f"RestartPolicyNever: {typ.value}-{i} failed "
                    f"({failed[-1].status.reason or 'no reason'})")
            elif failed and i in exhausted and not has_active and i not in done:
                # The restart policy engine gave up on this index: terminal,
                # exactly like restartPolicy Never, with the budget named.
                done[i] = PHASE_FAILED
                any_terminal_failure = True
                d = recovery.decision_for(typ, i)
                terminal_msgs.append(
                    f"BackoffLimitExceeded: {typ.value}-{i} failed "
                    f"{d.count if d else '?'} times "
                    f"(backoffLimit {job.spec.backoff_limit})")
            elif failed and replace_on_failure and not has_active:
                recovering = True
            if not plist:
                scheduled = False
            if typ == ReplicaType.SERVING:
                # Serving readiness = model loaded + first decode step:
                # the replica beats phase="serving" only past both.
                if not any(p.status.phase == PHASE_RUNNING
                           and p.status.progress is not None
                           and p.status.progress.phase == "serving"
                           for p in plist):
                    ready = False
            elif not any(p.status.phase == PHASE_RUNNING for p in plist) and i not in done:
                ready = False
        index_done[typ] = done

        status.tf_replica_statuses.append(
            TFReplicaStatus(
                type=typ,
                state=_aggregate_state(states, desired),
                pod_names=sorted(p.metadata.name for p in pods),
                tf_replicas_states=hist,
                restarts=(recovery.restarts_for(typ)
                          if recovery is not None else 0),
            )
        )

    # -- phase (replaces local.go:53-63 / distributed.go:47-59) --
    chief = _find_chief(job)
    phase = prev_phase
    if chief is not None:
        ctyp = ReplicaType(chief.tf_replica_name)
        outcome = index_done.get(ctyp, {}).get(chief.tf_replica_index)
        if outcome == PHASE_SUCCEEDED:
            phase = TFJobPhase.SUCCEEDED
        elif outcome == PHASE_FAILED:
            phase = TFJobPhase.FAILED
        else:
            phase = _running_or_pending(prev_phase, any_running)
    else:
        # Default rule: the job succeeds when every *deciding* replica index
        # succeeded.  PS replicas never decide (they run forever — ref:
        # distributed.go:51-55, mnist_replica.py:121-122); Serving replicas
        # never decide either — a serving job is long-running by contract
        # and never rolls up to Succeeded (a drained replica's Succeeded
        # exit is a rollout/scale-down artifact, not completion).
        deciding = [
            s for s in job.spec.tf_replica_specs
            if s.tf_replica_type not in (ReplicaType.PS, ReplicaType.SERVING)
        ]
        if any_terminal_failure:
            phase = TFJobPhase.FAILED
        elif deciding and all(
            len(index_done.get(s.tf_replica_type, {})) == gang_width(job, s)
            and all(v == PHASE_SUCCEEDED for v in index_done[s.tf_replica_type].values())
            for s in deciding
        ):
            phase = TFJobPhase.SUCCEEDED
        else:
            phase = _running_or_pending(prev_phase, any_running)
    # Terminal phases are sticky.
    if prev_phase in (TFJobPhase.SUCCEEDED, TFJobPhase.FAILED):
        phase = prev_phase
    status.phase = phase

    # Lifecycle telemetry: report the computed transition to the obs
    # tracker (dedup'd there — the controller recomputes status every sync,
    # often from a stale informer view, and only the first observation of a
    # transition may count).  Pure-function contract preserved: this is a
    # side channel, the returned status is unchanged.
    if phase != prev_phase:
        from ..obs import job_lifecycle

        job_lifecycle().observe(
            job.metadata.uid or f"{job.metadata.namespace}/{job.metadata.name}",
            prev_phase.value, phase.value, now=now,
            created=job.metadata.creation_timestamp)

    # -- conditions (populating types.go:154-161) --
    # The READY message carries the structured health report (checker/
    # health.py) so `describe` and the status surface tell one story.
    from ..checker import check_health

    health = check_health(
        job, pods_by_type, now=now, tracker=tracker,
        exhausted=({t.tf_replica_type: recovery.exhausted(t.tf_replica_type)
                    for t in job.spec.tf_replica_specs}
                   if recovery is not None else None))
    health_msg = "; ".join(
        f"{t.value}={rh.health.value} {rh.running}/{rh.desired} running"
        + (f", missing {rh.missing_indices}" if rh.missing_indices else "")
        + (f", stalled {rh.stalled_indices}" if rh.stalled_indices else "")
        for t, rh in health.replicas.items()
    )

    # -- training-plane progress rollup (net-new; PAPERS.md telemetry) --
    status.progress = compute_progress(
        job, pods_by_type,
        {t: rh.stalled_indices for t, rh in health.replicas.items()})
    if status.progress is not None and status.progress.straggler_lag > 0:
        health_msg += (f"; straggler lag={status.progress.straggler_lag} steps "
                       f"(step {status.progress.step}.."
                       f"{status.progress.max_step})")

    terminal = phase in (TFJobPhase.SUCCEEDED, TFJobPhase.FAILED)
    any_stalled = any(rh.stalled_indices for rh in health.replicas.values())
    # Recovery-plane terminal verdict: why the job failed, on the status
    # surface (`kctpu get` REASON column + the acceptance contract that a
    # killed Never-policy pod yields a POLICY condition, not a silent hang).
    if phase == TFJobPhase.FAILED and terminal_msgs:
        if not status.reason or status.reason.startswith(
                ("GangQueued", "BackoffLimitExceeded", "RestartPolicyNever")):
            status.reason = terminal_msgs[0]
    # Queue state surfaces as the job's Pending reason + Scheduled=False
    # (GangQueued) so `kctpu get` answers "why is this job not running".
    if gang_queue_msg and not terminal:
        status.reason = gang_queue_msg
        set_condition(status, TFJobConditionType.SCHEDULED, False,
                      reason="GangQueued", message=gang_queue_msg, now=now)
    else:
        if status.reason.startswith("GangQueued"):
            status.reason = ""
        set_condition(status, TFJobConditionType.SCHEDULED, scheduled,
                      reason="AllReplicasScheduled" if scheduled else "WaitingForReplicas", now=now)
    set_condition(status, TFJobConditionType.READY,
                  ready and not terminal and not any_stalled,
                  reason=("TrainingStalled" if any_stalled
                          else "AllReplicasReady" if ready
                          else "ReplicasNotReady"),
                  message=health_msg, now=now)
    if not recovering and phase == TFJobPhase.FAILED and terminal_msgs:
        # The recovery plane GAVE UP (backoff limit spent, or the policy
        # forbids restarts): Recovering=False carries the verdict.
        set_condition(status, TFJobConditionType.RECOVERING, False,
                      reason=terminal_msgs[0].split(":", 1)[0],
                      message="; ".join(terminal_msgs), now=now)
    else:
        set_condition(status, TFJobConditionType.RECOVERING, recovering,
                      reason=("GangPreempted" if recovering and gang_preempt_msg
                              else "ReplacingFailedReplicas" if recovering else ""),
                      message=gang_preempt_msg if recovering else "", now=now)
    has_active = any(
        is_pod_active(p) for pods in pods_by_type.values() for p in pods
    )
    set_condition(status, TFJobConditionType.RECYCLING, terminal and has_active,
                  reason="ReclaimingReplicas" if terminal and has_active else "", now=now)

    # -- elastic width rollup (net-new; elastic/engine.py drives it) --
    # Only elastic jobs carry the width status + Degraded condition, so
    # the pre-elastic status shape serializes unchanged for everyone else.
    from ..api.tfjob import JobWidth, elastic_gang_spec

    # -- serving rollup (net-new; serving plane) --
    status.serving = compute_serving(job, pods_by_type)

    el_spec = elastic_gang_spec(job)
    if el_spec is not None:
        w = gang_width(job, el_spec)
        full = spec_width(el_spec)
        status.width = JobWidth(current=w, spec=full,
                                min=max(1, job.spec.elastic.min_width))
        reduced = w < full
        set_condition(
            status, TFJobConditionType.DEGRADED, reduced,
            reason="WidthReduced" if reduced else "FullWidth",
            message=(f"elastic gang training at width {w}/{full} "
                     f"(floor {status.width.min}); replacement warming"
                     if reduced else ""),
            now=now)
    else:
        status.width = None
    return status


def _running_or_pending(prev: TFJobPhase, any_running: bool) -> TFJobPhase:
    if any_running or prev == TFJobPhase.RUNNING:
        return TFJobPhase.RUNNING
    return TFJobPhase.PENDING


def should_update(old: TFJobStatus, new: TFJobStatus) -> bool:
    """Semantic change detection — the deep-equal the reference lacked
    (local.go:65-79 rebuilds and always updates).  Transition timestamps are
    ignored so a no-op recompute never writes."""
    return _strip_times(serde.to_dict(old)) != _strip_times(serde.to_dict(new))


def _strip_times(d: dict) -> dict:
    for c in d.get("conditions", []) or []:
        c.pop("lastTransitionTime", None)
    return d
