"""Status rollup (ref: pkg/controller/updater/)."""

from .status import compute_status, set_condition, should_update  # noqa: F401
