"""Status rollup (ref: pkg/controller/updater/)."""

from .incremental import RollupCache  # noqa: F401
from .status import compute_status, set_condition, should_update  # noqa: F401
