"""Serving plane: controller-side autoscaling for continuous-batching
inference replicas (docs/SERVING.md).

The replica runtime lives in workloads/serve.py; this package is the
control-plane half — the hysteresis autoscaler the controller consults
every sync of a serving job."""

from .autoscale import AutoscaleDecision, ServingAutoscaler, serving_width

__all__ = ["AutoscaleDecision", "ServingAutoscaler", "serving_width"]
