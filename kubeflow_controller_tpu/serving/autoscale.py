"""Queue-depth autoscaler for Serving replica sets, with hysteresis.

Scaling signal: the per-replica intake-queue depth the replicas publish
through the progress plane (PodProgress.queue_depth).  The HPA formula
over the spec's ``autoscale.target_queue_depth``::

    desired = ceil(current * avg_queue_depth / target_queue_depth)

clamped to [min_replicas, max_replicas], with three hysteresis guards so
the target cannot flap around the setpoint (the failure mode the serving
tests gate):

- **tolerance band**: no scaling while |avg/target - 1| <= tolerance;
- **scale-up gating on readiness**: while previously-requested replicas
  are still warming (ready < current), the queue backlog they will absorb
  is already provisioned — requesting more would double-count it;
- **scale-down stabilization**: the signal must sit below the band
  CONTINUOUSLY for ``scale_down_stabilization_s`` before any replica is
  drained (a single quiet scrape never sheds capacity).

The autoscaler only picks the target; the planner executes it — scale-up
admits new replicas (warm pools + the AOT'd compile cache make them
cache-hit on spawn), scale-down drains the highest indices gracefully
(docs/SERVING.md "Scale-down and drain").

Deliberately assessment-driven (the controller calls :meth:`assess` from
its sync loop) and clock-injected, so hysteresis is unit-testable without
sleeping.
"""

from __future__ import annotations

import json
import math
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from ..api.core import PHASE_RUNNING, Pod
from ..api.labels import (ANNOTATION_GATEWAY_STATS,
                          ANNOTATION_SERVING_REPLICAS)
from ..api.tfjob import ReplicaType, TFJob, serving_spec
from ..utils import locks

# A gateway-stats annotation older than this is ignored: a dead gateway
# must not pin the scale signal to its last (possibly panicked) snapshot.
GATEWAY_STATS_STALE_S = 10.0


def gateway_signal(job: TFJob, now: float) -> Tuple[float, str]:
    """Demand the replicas never see, in queue-depth units: requests held
    in the gateway's admission queue plus one second's worth of sheds.
    Raw replica queue depth UNDER-counts once the gateway sheds — the
    shed traffic left no backlog anywhere — so without this term a
    shedding gateway masks exactly the overload that needs a scale-up."""
    raw = job.metadata.annotations.get(ANNOTATION_GATEWAY_STATS, "")
    if not raw:
        return 0.0, ""
    try:
        d = json.loads(raw)
    except ValueError:
        return 0.0, ""
    ts = float(d.get("ts", 0.0) or 0.0)
    if ts and now - ts > GATEWAY_STATS_STALE_S:
        return 0.0, ""
    queued = max(0, int(d.get("queued", 0) or 0))
    shed_rps = max(0.0, float(d.get("shed_rps", 0.0) or 0.0))
    extra = queued + shed_rps
    if not extra:
        return 0.0, ""
    return extra, f"gateway queued {queued} + shed {shed_rps:g}/s"


def serving_width(job: TFJob) -> int:
    """The Serving set's CURRENT replica target: the controller-written
    serving-replicas annotation, else autoscale.minReplicas, else
    spec.replicas — clamped to the autoscale bounds when present.  The
    planner, updater, health checker and CLI all key off this one
    function (the serving analog of the elastic gang_width)."""
    spec = serving_spec(job)
    if spec is None:
        return 0
    a = job.spec.autoscale
    default = a.min_replicas if a is not None else spec.replicas
    try:
        w = int(job.metadata.annotations.get(ANNOTATION_SERVING_REPLICAS, "")
                or default)
    except ValueError:
        return default
    if a is not None:
        return max(a.min_replicas, min(w, a.max_replicas))
    return max(0, w)


def replica_ready(pod: Pod) -> bool:
    """Serving readiness: Running AND past model load + first decode step
    (the replica beats phase="serving" only then)."""
    return (pod.status.phase == PHASE_RUNNING
            and pod.status.progress is not None
            and pod.status.progress.phase == "serving")


@dataclass
class AutoscaleDecision:
    """One assessment's outcome.  ``target`` is None when no change is
    wanted; ``requeue_after_s`` > 0 asks the controller to look again
    (a pending scale-down's stabilization window emits no watch events)."""

    target: Optional[int] = None
    reason: str = ""
    requeue_after_s: float = 0.0


class ServingAutoscaler:
    """Per-job scale assessment with the stabilization memory that makes
    scale-down deliberate.  Thread-safe: sync workers of different shards
    may assess different jobs concurrently."""

    def __init__(self):
        self._lock = locks.named_lock("serving.autoscaler")
        # job key -> wall clock when the signal first dropped below the
        # scale-down band (cleared whenever it rises back).
        self._below_since: Dict[str, float] = {}

    def forget_job(self, key: str) -> None:
        with self._lock:
            self._below_since.pop(key, None)

    def assess(self, key: str, job: TFJob, serving_pods: List[Pod],
               now: Optional[float] = None) -> AutoscaleDecision:
        a = job.spec.autoscale
        if a is None:
            return AutoscaleDecision()
        t = now if now is not None else time.time()
        current = serving_width(job)
        ready = [p for p in serving_pods if replica_ready(p)]
        if not ready:
            # Nothing reporting yet (cold start): hold at the current
            # target — there is no signal to scale on.
            with self._lock:
                self._below_since.pop(key, None)
            return AutoscaleDecision()
        total_depth = sum(p.status.progress.queue_depth for p in ready)
        gw_extra, gw_why = gateway_signal(job, t)
        total_depth += gw_extra
        avg = total_depth / len(ready)
        ratio = avg / a.target_queue_depth
        desired = max(a.min_replicas,
                      min(a.max_replicas,
                          math.ceil(current * ratio) if ratio > 0
                          else a.min_replicas))

        if ratio > 1.0 + a.tolerance and desired > current:
            with self._lock:
                self._below_since.pop(key, None)
            if len(ready) < current:
                # Requested capacity still warming: the backlog is already
                # provisioned for — asking again would overshoot.
                return AutoscaleDecision(
                    reason=f"holding at {current}: {len(ready)} ready, "
                           f"scale-up in progress")
            return AutoscaleDecision(
                target=desired,
                reason=f"queue depth avg {avg:.1f} > target "
                       f"{a.target_queue_depth:g} (x{ratio:.2f}"
                       + (f"; {gw_why}" if gw_why else "") + f"): "
                       f"{current} -> {desired}")

        if ratio < 1.0 - a.tolerance and current > a.min_replicas:
            with self._lock:
                since = self._below_since.setdefault(key, t)
            waited = t - since
            if waited < a.scale_down_stabilization_s:
                return AutoscaleDecision(
                    requeue_after_s=a.scale_down_stabilization_s - waited,
                    reason=f"below target for {waited:.1f}s; stabilizing")
            with self._lock:
                self._below_since.pop(key, None)
            target = max(desired, a.min_replicas)
            if target >= current:
                return AutoscaleDecision()
            return AutoscaleDecision(
                target=target,
                reason=f"queue depth avg {avg:.1f} < target "
                       f"{a.target_queue_depth:g} for "
                       f"{a.scale_down_stabilization_s:g}s: "
                       f"{current} -> {target}")

        # Inside the tolerance band (or already at a bound): steady.
        with self._lock:
            if ratio >= 1.0 - a.tolerance:
                self._below_since.pop(key, None)
        return AutoscaleDecision()


def serving_pods_of(pods_by_type) -> List[Pod]:
    return pods_by_type.get(ReplicaType.SERVING, [])
