"""The diff engine: desired state vs observed pods/services -> ordered events.

Successor of ``Job.Action()`` (ref: pkg/tensorflow/local.go:54-77,
distributed.go:59-117).  The reference compares bare counts; this planner
diffs **per replica index**, which is what makes failure recovery, service
repair, scale-down and TPU gangs expressible at all (SURVEY.md §7 step 4).

Event ordering preserves the reference's invariant — services before pods,
workers before PS (ref: distributed.go:59-117) — so that a pod's generated
cluster spec always refers to services that already exist.

Replacement policy (net-new; the reference observes failures and does
nothing, design_doc.md:228-260):

- template restartPolicy OnFailure/Always -> a Failed pod is deleted and
  re-created **at the same index** (in-place kubelet restarts handle crash
  loops first; a Failed phase means those were exhausted);
- restartPolicy Never -> the failure is terminal; the planner leaves it for
  the updater to roll up into phase=Failed;
- a TPU gang is one failure domain: any Failed TPU pod fails the whole gang,
  and the planner replaces the **entire** gang at once (torn collectives
  cannot be rejoined process-by-process).

Terminal jobs (Succeeded/Failed) get cleanup-only plans: active pods and all
services are deleted — the "Recycling" step the reference declared but never
implemented (types.go:158-160, SURVEY.md §3.5).  Terminated pods are kept as
records, as k8s Jobs do.
"""

from __future__ import annotations

from typing import Dict, List

from ..api.core import (
    PHASE_FAILED,
    PHASE_SUCCEEDED,
    Pod,
    Service,
    is_pod_active,
)
from ..api.tfjob import ReplicaType, TFJob, TFJobPhase, TFReplicaSpec, tpu_total_hosts
from .materialize import gang_generation, gang_width, pods_by_index, services_by_index
from .types import Action, Plan, PlanEvent

# Service/pod ordering across types (ref: distributed.go:59-117 emits worker
# services, PS services, worker pods, PS pods — generalized here).
_TYPE_ORDER = [ReplicaType.WORKER, ReplicaType.PS, ReplicaType.TPU,
               ReplicaType.SERVING, ReplicaType.LOCAL]


def desired_replicas(spec: TFReplicaSpec) -> int:
    """TPU replica count is the topology's host count across all slices —
    the TPUSpec is the source of truth (spec.replicas must agree; validated
    at the API)."""
    if spec.tf_replica_type == ReplicaType.TPU and spec.tpu is not None:
        return tpu_total_hosts(spec.tpu)
    return spec.replicas


def desired_service_indices(spec: TFReplicaSpec, job: TFJob = None) -> range:
    typ = spec.tf_replica_type
    if typ in (ReplicaType.PS, ReplicaType.WORKER, ReplicaType.SERVING):
        # Elastic gangs / autoscaled Serving sets: one service per CURRENT
        # member (extra indices are scaled down while degraded, re-created
        # on scale-up — service names are deterministic, so repair is
        # index-exact).
        n = gang_width(job, spec) if job is not None else desired_replicas(spec)
        return range(n)
    if typ == ReplicaType.TPU:
        return range(1)  # only the coordinator service (replica 0)
    return range(0)  # Local: no services (ref: local.go)


def _ordered_specs(job: TFJob) -> List[TFReplicaSpec]:
    return sorted(
        job.spec.tf_replica_specs,
        key=lambda s: _TYPE_ORDER.index(s.tf_replica_type),
    )


def plan_job(
    job: TFJob,
    pods_by_type: Dict[ReplicaType, List[Pod]],
    services_by_type: Dict[ReplicaType, List[Service]],
    recovery=None,
) -> Plan:
    """``recovery`` (optional) is a RecoveryAssessment from the restart
    policy engine (recovery/policy.py): indices in backoff are left alone
    this sync (the controller requeues after the delay), indices whose
    backoff limit is exhausted are terminal (the updater fails the job)."""
    if job.status.phase in (TFJobPhase.SUCCEEDED, TFJobPhase.FAILED):
        return _plan_cleanup(job, pods_by_type, services_by_type)

    events: List[PlanEvent] = []
    # Pass 1: services (so cluster specs always resolve).
    for spec in _ordered_specs(job):
        typ = spec.tf_replica_type
        by_idx = services_by_index(services_by_type.get(typ, []))
        want = desired_service_indices(spec, job)
        for i in want:
            if not by_idx.get(i):
                events.append(PlanEvent(Action.ADD_SERVICE, typ, index=i))
        for i, svcs in sorted(by_idx.items()):
            if i not in want:
                for s in svcs:
                    events.append(
                        PlanEvent(Action.DELETE_SERVICE, typ, index=i,
                                  name=s.metadata.name, reason="scale-down")
                    )
    # Pass 2: pods.
    for spec in _ordered_specs(job):
        events.extend(_plan_pods(
            job, spec, pods_by_type.get(spec.tf_replica_type, []), recovery))
    return Plan(events)


def is_gang_spec(spec: TFReplicaSpec) -> bool:
    """One failure domain, replaced as a unit: TPU slices always; Worker
    gangs via the explicit spec.gang_restart opt-in (a multi-process
    jax.distributed gang's torn collective cannot be rejoined per-index)."""
    return spec.tf_replica_type == ReplicaType.TPU or spec.gang_restart


def _gate(recovery, typ: ReplicaType, index: int) -> str:
    """The restart-policy verdict for a failed index: "replace" without an
    engine (the pre-recovery behavior, kept for pure-planner callers)."""
    if recovery is None:
        return "replace"
    d = recovery.decision_for(typ, index)
    return d.action if d is not None else "replace"


def _plan_pods(job: TFJob, spec: TFReplicaSpec, pods: List[Pod],
               recovery=None) -> List[PlanEvent]:
    typ = spec.tf_replica_type
    # Elastic gangs plan at the CURRENT width (the controller-written
    # annotation); everything else at the spec width.
    n = gang_width(job, spec)
    by_idx = pods_by_index(pods)
    restart = (spec.template.spec.restart_policy if spec.template else "OnFailure")
    replace_on_failure = restart in ("OnFailure", "Always")

    events: List[PlanEvent] = []

    if typ == ReplicaType.SERVING:
        return _plan_serving(job, spec, n, by_idx, recovery)
    if is_gang_spec(spec):
        return _plan_gang(job, spec, n, by_idx, replace_on_failure, recovery)

    for i in range(n):
        plist = sorted(by_idx.get(i, []), key=lambda p: p.metadata.creation_timestamp or 0)
        active = [p for p in plist if is_pod_active(p)]
        succeeded = any(p.status.phase == PHASE_SUCCEEDED for p in plist)
        failed = [p for p in plist if p.status.phase == PHASE_FAILED]
        if active:
            # Duplicate actives at one index (e.g. after adoption): keep the
            # oldest, delete the rest.
            for extra in active[1:]:
                events.append(PlanEvent(Action.DELETE_POD, typ, index=i,
                                        name=extra.metadata.name, reason="duplicate-index"))
            continue
        if succeeded and typ != ReplicaType.PS:
            continue  # this index is done (finer-grained than the
            # count-based `Replicas - succeeded` at distributed.go:63)
        if failed and not replace_on_failure:
            continue  # terminal failure: updater rolls up phase=Failed
        if failed:
            verdict = _gate(recovery, typ, i)
            if verdict in ("backoff", "exhausted", "never"):
                # backoff: wait out the window (controller requeues);
                # exhausted/never: terminal, updater fails the job.
                continue
            # Index-preserving replacement: clear the failed record(s) and
            # re-create at the same index.
            for p in failed:
                events.append(PlanEvent(Action.DELETE_POD, typ, index=i,
                                        name=p.metadata.name, reason="replace-failed"))
        events.append(PlanEvent(Action.ADD_POD, typ, index=i,
                                reason="replace-failed" if failed else ""))
    # Scale-down: indices beyond the desired count.
    for i, plist in sorted(by_idx.items()):
        if i >= n:
            for p in plist:
                if is_pod_active(p):
                    events.append(PlanEvent(Action.DELETE_POD, typ, index=i,
                                            name=p.metadata.name, reason="scale-down"))
    return events


def _is_draining(p: Pod) -> bool:
    from ..api.labels import ANNOTATION_DRAIN

    return bool(p.metadata.annotations.get(ANNOTATION_DRAIN))


def _serving_ready(p: Pod) -> bool:
    from ..api.core import PHASE_RUNNING

    return (p.status.phase == PHASE_RUNNING
            and p.status.progress is not None
            and p.status.progress.phase == "serving")


def _plan_serving(job: TFJob, spec: TFReplicaSpec, n: int,
                  by_idx: Dict[int, List[Pod]], recovery=None) -> List[PlanEvent]:
    """Long-running Serving replicas: keep ``n`` (the autoscaler's current
    target) servers alive, drain gracefully instead of killing, and roll
    weight updates one replica at a time.

    - index < n, no active pod: create (a Succeeded record there means the
      server EXITED — drained by a rollout or crashed clean — and is
      replaced, unlike batch workers, where Succeeded means done; Failed
      records go through the restart-policy gate like any replica).
    - index >= n (scale-down) and active: emit ``DrainPod`` once — the
      replica stops intake, finishes in-flight requests, and exits; its
      terminal record is then deleted.  Never a hard delete of a serving
      pod that hasn't drained.
    - **rolling update**: an active pod whose gang-generation annotation
      lags the job's carries the PREVIOUS weights.  Drain AT MOST ONE
      stale replica at a time, and only while every other in-target
      replica is ready — zero dropped requests, max-unavailable 1 (the
      PR 9 gang-generation machinery, reused as the weights version)."""
    typ = spec.tf_replica_type
    events: List[PlanEvent] = []
    expected_gen = gang_generation(job)

    stale_active: List[tuple] = []
    ready_total = 0  # ready, not draining, in-target — ANY generation
    draining_count = 0
    for i, plist in sorted(by_idx.items()):
        for p in plist:
            if not is_pod_active(p):
                continue
            if _is_draining(p):
                draining_count += 1
                continue
            if i < n and _serving_ready(p):
                ready_total += 1
            if i < n and _pod_generation(p) != expected_gen:
                stale_active.append((i, p))

    for i in range(n):
        plist = sorted(by_idx.get(i, []),
                       key=lambda p: p.metadata.creation_timestamp or 0)
        active = [p for p in plist if is_pod_active(p)]
        if active:
            for extra in active[1:]:
                events.append(PlanEvent(Action.DELETE_POD, typ, index=i,
                                        name=extra.metadata.name,
                                        reason="duplicate-index"))
            continue
        failed = [p for p in plist if p.status.phase == PHASE_FAILED]
        if failed:
            verdict = _gate(recovery, typ, i)
            if verdict in ("backoff", "exhausted", "never"):
                continue
        # Clear terminal records (drained rollout exits and cleared
        # failures) and re-create at the same index: a serving index is
        # never "done".
        for p in plist:
            events.append(PlanEvent(
                Action.DELETE_POD, typ, index=i, name=p.metadata.name,
                reason="replace-failed" if failed else "rollout"))
        events.append(PlanEvent(Action.ADD_POD, typ, index=i,
                                reason="replace-failed" if failed else ""))

    # Scale-down: indices beyond the target drain gracefully, then their
    # terminal records are cleared.
    for i, plist in sorted(by_idx.items()):
        if i < n:
            continue
        for p in plist:
            if is_pod_active(p):
                if not _is_draining(p):
                    events.append(PlanEvent(Action.DRAIN_POD, typ, index=i,
                                            name=p.metadata.name,
                                            reason="scale-down"))
            else:
                events.append(PlanEvent(Action.DELETE_POD, typ, index=i,
                                        name=p.metadata.name,
                                        reason="scale-down"))

    # Rolling weight update: one stale replica drains only while the whole
    # target set is ready (old weights serve fine mid-roll) and nothing
    # else is mid-drain — max-unavailable 1.  With n == 1 the single
    # replica drains and its replacement follows (a brief intake gap the
    # front end bridges by queueing; docs/SERVING.md).
    if stale_active and draining_count == 0 and ready_total >= n:
        i, p = stale_active[0]
        events.append(PlanEvent(Action.DRAIN_POD, typ, index=i,
                                name=p.metadata.name, reason="rollout"))
    return events


def _pod_generation(p: Pod) -> int:
    from ..api.labels import ANNOTATION_GANG_GENERATION

    try:
        return int(p.metadata.annotations.get(
            ANNOTATION_GANG_GENERATION, "0") or "0")
    except ValueError:
        return 0


def _plan_gang(
    job: TFJob, spec: TFReplicaSpec, n: int, by_idx: Dict[int, List[Pod]],
    replace_on_failure: bool, recovery=None
) -> List[PlanEvent]:
    """All-or-nothing: if any member failed (and we replace), tear down every
    surviving member and re-create the full gang.  Under the restart policy
    engine, the whole gang waits out the worst failed member's backoff and
    goes terminal if ANY member's limit is exhausted (one failure domain —
    its restart budget is shared).

    Width transitions (elastic plane) ride the generation: active members
    whose gang-generation annotation lags the job's mean the controller
    has driven a re-shard (degrade / harvest / re-expand) — the STALE gang
    is replaced wholesale at the CURRENT width ``n``, without waiting out
    anyone's backoff (the survivors are healthy; the point of the
    transition is to keep them training)."""
    typ = spec.tf_replica_type
    events: List[PlanEvent] = []
    failed_indices = [
        i for i, plist in by_idx.items()
        if any(p.status.phase == PHASE_FAILED for p in plist)
        and not any(is_pod_active(p) for p in plist)
    ]
    any_failed = any(
        p.status.phase == PHASE_FAILED for plist in by_idx.values() for p in plist
    )
    all_succeeded = n > 0 and all(
        any(p.status.phase == PHASE_SUCCEEDED for p in by_idx.get(i, [])) for i in range(n)
    )
    if all_succeeded:
        return events
    expected_gen = gang_generation(job)
    stale = any(
        _pod_generation(p) != expected_gen
        for plist in by_idx.values() for p in plist if is_pod_active(p))
    if stale and replace_on_failure:
        verdicts = [_gate(recovery, typ, i) for i in failed_indices]
        if "exhausted" in verdicts:
            return events  # terminal: the gang's restart budget is spent
        for i, plist in sorted(by_idx.items()):
            for p in plist:
                events.append(PlanEvent(Action.DELETE_POD, typ, index=i,
                                        name=p.metadata.name,
                                        reason="reshard"))
        for i in range(n):
            events.append(PlanEvent(Action.ADD_POD, typ, index=i,
                                    reason="reshard"))
        return events
    if any_failed and replace_on_failure:
        verdicts = [_gate(recovery, typ, i) for i in failed_indices]
        if "exhausted" in verdicts:
            return events  # terminal: the gang's restart budget is spent
        if "backoff" in verdicts or not failed_indices:
            # Waiting out a member's backoff (controller requeues), or the
            # failure is already being replaced (active pod at the index).
            return events
        # Delete EVERY member record — including Succeeded ones — so stale
        # results cannot mix with the replacement gang's (a fresh gang is a
        # fresh jax.distributed world; old per-host outcomes are void).
        for i, plist in sorted(by_idx.items()):
            for p in plist:
                events.append(PlanEvent(Action.DELETE_POD, typ, index=i,
                                        name=p.metadata.name, reason="gang-replace"))
        for i in range(n):
            events.append(PlanEvent(Action.ADD_POD, typ, index=i,
                                    reason="gang-replace"))
        return events
    if any_failed:
        return events  # terminal: updater fails the job
    for i in range(n):
        plist = by_idx.get(i, [])
        if not any(is_pod_active(p) or p.status.phase == PHASE_SUCCEEDED for p in plist):
            events.append(PlanEvent(Action.ADD_POD, typ, index=i))
    # Scale-down beyond the slice host count.
    for i, plist in sorted(by_idx.items()):
        if i >= n:
            for p in plist:
                if is_pod_active(p):
                    events.append(PlanEvent(Action.DELETE_POD, typ, index=i,
                                            name=p.metadata.name, reason="scale-down"))
    return events


def _plan_cleanup(
    job: TFJob,
    pods_by_type: Dict[ReplicaType, List[Pod]],
    services_by_type: Dict[ReplicaType, List[Service]],
) -> Plan:
    events: List[PlanEvent] = []
    for typ, svcs in services_by_type.items():
        for s in svcs:
            events.append(PlanEvent(Action.DELETE_SERVICE, typ,
                                    name=s.metadata.name, reason="recycle"))
    for typ, pods in pods_by_type.items():
        for p in pods:
            if is_pod_active(p):
                events.append(PlanEvent(Action.DELETE_POD, typ,
                                        name=p.metadata.name, reason="recycle"))
    return Plan(events)
