"""Mesh-to-slice mapper: split declared parallelism axes across the
slice topology the scheduler placed.

A ``TPUSpec.mesh`` names logical axes (pp/dp/fsdp/tp/sp/ep) without
saying where they live.  The physics decide (PAPERS.md — the pod-scale
decompositions): pipeline (pp) and data (dp) parallelism tolerate the
DCN's latency because they exchange small activations/gradients on a
coarse cadence, while fsdp/tp/sp shuffle whole parameter shards every
layer and must stay on ICI inside one slice.  So the mapper factors the
mesh as

    inter-slice (DCN):  pp  ×  dp_inter  (= num_slices / pp)
    intra-slice (ICI):  dp_intra (= dp / dp_inter) × fsdp × tp × sp × ep

and recomputes the DCN share at the gang's *current* width — elastic
degrade removes whole inter-slice dp replicas (never a pipeline stage),
so ``dp`` shrinks by exactly ``dp_intra`` per released pipeline span
while every other axis is untouched.  The materializer serializes the
current-width axes into ``$KCTPU_MESH`` so the workload builds the same
global mesh the scheduler placed, instead of re-deriving shape from
spec.replicas.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional

from ..api.tfjob import (
    TPUSpec,
    ValidationError,
    mesh_pp_span,
    tpu_slice_hosts,
)

# Axes that must stay inside one slice (ICI-hungry: per-layer collectives).
ICI_AXES = ("fsdp", "tp", "sp", "ep")


@dataclass(frozen=True)
class MeshSlicePlan:
    """The factored mesh at a concrete slice count."""

    # Global mesh axes at the current width — what $KCTPU_MESH carries
    # and the workload hands to build_mesh.
    axes: Dict[str, int] = field(default_factory=dict)
    # DCN factors: {"pp": ..., "dp": dp_inter} — axes (shares) that span
    # slices.  ICI factors: everything that stays inside one slice,
    # including dp's intra-slice share.
    inter: Dict[str, int] = field(default_factory=dict)
    intra: Dict[str, int] = field(default_factory=dict)
    num_slices: int = 1
    # Slices one pipeline replica spans == the slice-granularity of any
    # width change (the mesh-integrity unit, in slices).
    pp_span: int = 1
    dp_inter: int = 1
    dp_intra: int = 1

    def axis_scope(self) -> Dict[str, str]:
        """axis -> "dcn" | "ici" | "dcn x ici" — the describe view."""
        out: Dict[str, str] = {}
        for axis in self.axes:
            if axis == "pp":
                out[axis] = "dcn" if self.num_slices > 1 else "ici"
            elif axis == "dp":
                if self.dp_inter > 1 and self.dp_intra > 1:
                    out[axis] = "dcn x ici"
                elif self.dp_inter > 1:
                    out[axis] = "dcn"
                else:
                    out[axis] = "ici"
            else:
                out[axis] = "ici"
        return out


def mesh_slice_unit(tpu: Optional[TPUSpec]) -> int:
    """Width-change granularity in HOSTS: hosts-per-slice x pp.  One
    inter-slice dp replica spans pp slices; degrading by anything finer
    would orphan a pipeline stage."""
    if tpu is None:
        return 1
    return tpu_slice_hosts(tpu) * mesh_pp_span(tpu)


def plan_mesh_slices(tpu: TPUSpec,
                     num_slices_now: Optional[int] = None) -> MeshSlicePlan:
    """Factor ``tpu.mesh`` across ``num_slices_now`` slices (default: the
    spec's full slice count).  Raises ValidationError when the mesh does
    not divide — full-width divisibility is also enforced at admission by
    :func:`~..api.tfjob.validate_tpu_spec`."""
    full = max(1, tpu.num_slices)
    now = full if num_slices_now is None else max(1, num_slices_now)
    if not tpu.mesh:
        return MeshSlicePlan(axes={}, inter={}, intra={}, num_slices=now)
    pp = mesh_pp_span(tpu)
    if full % pp != 0:
        raise ValidationError(
            f"mesh.pp ({pp}) must divide numSlices ({full})")
    # A degraded width that is not a whole number of pipeline replicas
    # cannot host the mesh; use the largest width that is.  The elastic
    # engine rounds targets to this unit so in practice now == effective.
    effective = max(pp, (now // pp) * pp)
    dp_inter_full = full // pp
    dp_full = int(tpu.mesh.get("dp", 1) or 1)
    if dp_inter_full > 1 and dp_full % dp_inter_full != 0:
        raise ValidationError(
            f"mesh.dp ({dp_full}) must be divisible by the inter-slice "
            f"share numSlices/pp ({dp_inter_full})")
    dp_intra = dp_full // dp_inter_full if dp_inter_full > 1 else dp_full
    if dp_inter_full == 1:
        # All of dp fits in one slice-span; nothing to shrink over DCN.
        dp_intra = dp_full
    dp_inter_now = effective // pp
    dp_now = dp_intra * dp_inter_now if dp_inter_full > 1 else dp_full
    axes = {k: int(v) for k, v in tpu.mesh.items()}
    axes["dp"] = dp_now
    if pp > 1 or "pp" in tpu.mesh:
        axes["pp"] = pp
    intra = {"dp": dp_intra}
    for axis in ICI_AXES:
        if axis in axes:
            intra[axis] = axes[axis]
    return MeshSlicePlan(
        axes=axes,
        inter={"pp": pp, "dp": dp_inter_now},
        intra=intra,
        num_slices=effective,
        pp_span=pp,
        dp_inter=dp_inter_now,
        dp_intra=dp_intra,
    )
