"""Materializers: replica (type, index) -> concrete Pod / Service objects.

Successor of GetSpec/GetService/generateTFClusterSpec (ref: pkg/tensorflow/
distributed.go:120-191), with two deliberate redesigns:

1. **Deep copy before mutation.**  The reference rewrites the shared
   template's args per index (distributed.go:123-125 "TODO: check this
   override"), so concurrently-built replicas see each other's task_index.
   Every materializer here starts from ``serde.deep_copy``.

2. **Deterministic service names.**  The reference names services
   ``<job>-<type>-<idx>-<rand5>`` via generateName and must thread a
   ``serviceNames`` side table into arg generation (distributed.go:164-191).
   Deterministic names ``<job>-<rid>-<type><idx>`` make the cluster spec a
   pure function of the job — enabling per-index service repair and the
   single-coordinator TPU wiring with no bookkeeping.

TF PS/Worker replicas get the classic CLI contract (``--worker_hosts=…``,
``--ps_hosts=…``, ``--job_name=…``, ``--task_index=N``, port 2222 — ref:
distributed.go:29-32, 130-162).  TPU replicas get the ``jax.distributed``
contract instead (SURVEY.md §2.4): one well-known coordinator service plus
per-process env, and a ``google.com/tpu`` chip request — never
``nvidia.com/gpu``.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ..api.core import ContainerPort, Pod, Service, ServicePort
from ..api.labels import (
    ANNOTATION_ACCELERATOR,
    ANNOTATION_GANG_NAME,
    ANNOTATION_GANG_SIZE,
    ANNOTATION_NUM_SLICES,
    ANNOTATION_PRIORITY_CLASS,
    ANNOTATION_SLICE_INDEX,
    ANNOTATION_TENANT,
    LABEL_INDEX,
    selector_for,
)
from ..api.core import RESOURCE_TPU
from ..api.tenant import tenant_of
from ..api.tfjob import (
    ReplicaType,
    TFJob,
    TFReplicaSpec,
    replica_spec_for,
    tpu_slice_hosts,
    tpu_total_hosts,
)
from ..utils import serde

# The reference hardcodes TF grpc port 2222 (distributed.go:31-32).
TF_PORT = 2222

# Env contract consumed by the JAX workload layer (workloads/runtime.py).
ENV_COORDINATOR = "JAX_COORDINATOR_ADDRESS"
ENV_NUM_PROCESSES = "JAX_NUM_PROCESSES"
ENV_PROCESS_ID = "JAX_PROCESS_ID"
ENV_TPU_WORKER_ID = "TPU_WORKER_ID"
ENV_TPU_WORKER_HOSTNAMES = "TPU_WORKER_HOSTNAMES"
ENV_TPU_ACCELERATOR = "TPU_ACCELERATOR_TYPE"
# Multislice (DCN) contract — the names GKE multislice / megascale use.
ENV_NUM_SLICES = "MEGASCALE_NUM_SLICES"
ENV_SLICE_ID = "MEGASCALE_SLICE_ID"
# Slice-local coordinator (host 0 of THIS pod's slice): intra-slice
# rendezvous / per-slice rollup target, vs ENV_COORDINATOR which is the
# one global jax.distributed coordinator on slice 0.
ENV_SLICE_COORDINATOR = "MEGASCALE_COORDINATOR_ADDRESS"
# Mesh-to-slice plan (planner/meshmap.py): JSON of the GLOBAL mesh axes
# at the gang's current width, e.g. {"dp": 2, "fsdp": 4, "pp": 2}.
# Workloads build their device mesh from this — the shape the scheduler
# actually placed — never by re-deriving axis sizes from spec.replicas
# (the `kctpu vet` mesh-env rule).
ENV_MESH = "KCTPU_MESH"
# Per-job persistent compile cache (workloads/compile_cache.py): rides the
# pod spec like the *Dir fields, so replacements and warm readmissions of
# the gang land on the SAME populated cache and skip trace+XLA entirely.
ENV_COMPILE_CACHE = "KCTPU_COMPILE_CACHE"
# Recovery plane (recovery/): the controller-bumped gang generation a
# replacement gang rendezvouses under, the gang identity for the
# workload-side guard, and the periodic checkpoint interval.
ENV_GANG_GENERATION = "KCTPU_GANG_GENERATION"
ENV_GANG_NAME_WORKLOAD = "KCTPU_GANG_NAME"
ENV_CHECKPOINT_EVERY = "KCTPU_CHECKPOINT_EVERY"
# Elastic plane: the gang's CURRENT width, per generation.  Workloads
# derive data sharding and collective topology from this (and from the
# jax runtime it configures), never from spec.replicas — the invariant
# `kctpu vet` rule gang-width-env enforces.
ENV_GANG_WIDTH = "KCTPU_GANG_WIDTH"


def trace_context_for(job: TFJob):
    """The job's causal :class:`~..obs.trace.TraceContext`: the TFJob
    annotation when the controller stamped one (authoritative — it fixes
    the sampling decision), else derived deterministically from the uid,
    so planner and controller agree even before the first status write."""
    from ..api.labels import ANNOTATION_TRACE_CONTEXT
    from ..obs.trace import TraceContext

    ctx = TraceContext.decode(
        job.metadata.annotations.get(ANNOTATION_TRACE_CONTEXT, ""))
    if ctx is not None:
        return ctx
    return TraceContext.for_job(job.metadata.uid) if job.metadata.uid else None


def labels_for(job: TFJob, typ: ReplicaType) -> Dict[str, str]:
    """The 4-label replica selector (ref: getLabels, distributed.go:224-231)."""
    return selector_for(job.metadata.name, typ.value, job.spec.runtime_id)


def service_name(job: TFJob, typ: ReplicaType, index: int) -> str:
    """Deterministic, DNS-1123, <= 63 chars.

    Truncation (for long job names) removes characters from the *job name*,
    never from the runtime-id/type/index suffix — names for different
    replicas must stay distinct.

    TPU replicas share ONE headless subdomain service per slice (no index):
    per-host DNS is ``host-<i>.<subdomain>``, the GKE TPU pattern, rather
    than one ClusterIP service per replica as the TF PS/Worker path uses.
    """
    if typ == ReplicaType.TPU:
        suffix = f"-{job.spec.runtime_id}-tpu"
    else:
        suffix = f"-{job.spec.runtime_id}-{typ.value.lower()}{index}"
    base = job.metadata.name[: 63 - len(suffix)]
    return base + suffix


def tpu_host_dns(job: TFJob, index: int) -> str:
    """Stable per-host DNS name: ``host-<i>.<headless-subdomain>``."""
    return f"host-{index}.{service_name(job, ReplicaType.TPU, 0)}"


def coordinator_service_name(job: TFJob) -> str:
    """The jax.distributed coordinator address is host 0 of the slice's
    headless subdomain (SURVEY.md §5 'distributed communication backend')."""
    return tpu_host_dns(job, 0)


def gang_name(job: TFJob) -> str:
    return f"{job.metadata.name}-{job.spec.runtime_id}"


def pod_index(pod: Pod) -> Optional[int]:
    v = pod.metadata.labels.get(LABEL_INDEX)
    try:
        return int(v) if v is not None else None
    except ValueError:
        return None


def pods_by_index(pods: List[Pod]) -> Dict[int, List[Pod]]:
    out: Dict[int, List[Pod]] = {}
    for p in pods:
        i = pod_index(p)
        if i is not None:
            out.setdefault(i, []).append(p)
    return out


def services_by_index(services: List[Service]) -> Dict[int, List[Service]]:
    out: Dict[int, List[Service]] = {}
    for s in services:
        v = s.metadata.labels.get(LABEL_INDEX)
        if v is None:
            continue
        try:
            out.setdefault(int(v), []).append(s)
        except ValueError:
            continue
    return out


# ---------------------------------------------------------------------------
# Cluster-spec generation
# ---------------------------------------------------------------------------

def tf_cluster_args(job: TFJob, typ: ReplicaType, index: int) -> List[str]:
    """The classic TF PS/Worker CLI contract
    (ref: generateTFClusterSpec, distributed.go:130-162)."""
    worker = replica_spec_for(job, ReplicaType.WORKER)
    ps = replica_spec_for(job, ReplicaType.PS)
    worker_hosts = ",".join(
        f"{service_name(job, ReplicaType.WORKER, i)}:{TF_PORT}"
        for i in range(worker.replicas if worker else 0)
    )
    ps_hosts = ",".join(
        f"{service_name(job, ReplicaType.PS, i)}:{TF_PORT}"
        for i in range(ps.replicas if ps else 0)
    )
    args = []
    if worker_hosts:
        args.append(f"--worker_hosts={worker_hosts}")
    if ps_hosts:
        args.append(f"--ps_hosts={ps_hosts}")
    args.append(f"--job_name={'ps' if typ == ReplicaType.PS else 'worker'}")
    args.append(f"--task_index={index}")
    return args


def _dir_env(job: TFJob) -> Dict[str, str]:
    """Plumb the spec's reserved *Dir fields into replica env — they were
    declared and never read upstream (types.go:44-51; SURVEY.md §5
    checkpoint/resume)."""
    out = {}
    if job.spec.data_dir:
        out["DATA_DIR"] = job.spec.data_dir
    if job.spec.model_dir:
        out["MODEL_DIR"] = job.spec.model_dir
    if job.spec.log_dir:
        out["LOG_DIR"] = job.spec.log_dir
    if job.spec.export_dir:
        out["EXPORT_DIR"] = job.spec.export_dir
    if job.spec.compile_cache_dir:
        out[ENV_COMPILE_CACHE] = job.spec.compile_cache_dir
    if job.spec.checkpoint_every_steps > 0:
        out[ENV_CHECKPOINT_EVERY] = str(job.spec.checkpoint_every_steps)
    return out


def gang_generation(job: TFJob) -> int:
    """The job's current gang generation (controller-bumped annotation;
    0 = first incarnation)."""
    from ..api.labels import ANNOTATION_GANG_GENERATION

    try:
        return int(job.metadata.annotations.get(
            ANNOTATION_GANG_GENERATION, "0") or "0")
    except ValueError:
        return 0


def spec_width(spec: TFReplicaSpec) -> int:
    """The replica set's FULL width: the slice topology's host count for
    TPU (the source of truth), spec.replicas otherwise."""
    if spec.tf_replica_type == ReplicaType.TPU and spec.tpu is not None:
        return tpu_total_hosts(spec.tpu)
    return spec.replicas


def gang_width(job: TFJob, spec: TFReplicaSpec) -> int:
    """The replica set's CURRENT runtime width.

    For the job's elastic gang this is the controller-written gang-width
    annotation (bumped in lockstep with the gang generation on every
    re-shard transition), clamped to [elastic.min_width, spec width];
    for a Serving set it is the autoscaler-written serving-replicas
    annotation (serving/autoscale.py serving_width — scale is a runtime
    property exactly like elastic width); for everything else — and for
    an absent/invalid annotation — it is the spec width.  Planner,
    materializer, updater and health checker all key off this one
    function, so a width transition is one annotation write."""
    from ..api.labels import ANNOTATION_GANG_WIDTH
    from ..api.tfjob import elastic_gang_spec

    if spec.tf_replica_type == ReplicaType.SERVING:
        from ..serving.autoscale import serving_width

        return serving_width(job)
    full = spec_width(spec)
    if elastic_gang_spec(job) is not spec:
        return full
    try:
        w = int(job.metadata.annotations.get(ANNOTATION_GANG_WIDTH, "")
                or full)
    except ValueError:
        return full
    return max(max(1, job.spec.elastic.min_width), min(w, full))


# ---------------------------------------------------------------------------
# Pod / Service materializers
# ---------------------------------------------------------------------------

def make_pod(job: TFJob, spec: TFReplicaSpec, index: int) -> Pod:
    """Build the pod for replica (spec.tf_replica_type, index)."""
    typ = spec.tf_replica_type
    template = serde.deep_copy(spec.template)
    pod = Pod(metadata=template.metadata, spec=template.spec)
    pod.metadata.namespace = job.metadata.namespace
    pod.metadata.name = ""
    pod.metadata.generate_name = f"{job.metadata.name}-{typ.value.lower()}-{index}-"
    pod.metadata.labels = {**pod.metadata.labels, **labels_for(job, typ),
                           LABEL_INDEX: str(index)}
    # Resolved tenant identity rides every member pod so the scheduler's
    # DRF ledger and the apiserver's write accounting never need a TFJob
    # lookup (api/tenant.py is the only resolver).
    pod.metadata.annotations = {
        **pod.metadata.annotations,
        ANNOTATION_TENANT: tenant_of(job),
    }
    c = pod.spec.containers[0]
    for name, value in _dir_env(job).items():
        c.set_env(name, value)
    _stamp_trace_context(job, pod, c)

    if typ in (ReplicaType.PS, ReplicaType.WORKER):
        c.args = list(c.args) + tf_cluster_args(job, typ, index)
        if not any(p.container_port == TF_PORT for p in c.ports):
            c.ports.append(ContainerPort(name="tf-port", container_port=TF_PORT))
        if typ == ReplicaType.WORKER:
            _wire_worker_collectives(job, pod, c, index)
    elif typ == ReplicaType.TPU:
        _wire_tpu_pod(job, spec, pod, index)
    elif typ == ReplicaType.SERVING:
        _wire_serving_pod(job, spec, pod, index)
    # Local: no wiring at all (ref: local.go — single pod, no services).
    return pod


def _stamp_trace_context(job: TFJob, pod: Pod, c) -> None:
    """Causal-context plumbing (obs plane): the pod annotation lets the
    scheduler/kubelet attach their spans to the job's trace, and the env
    var hands the context to the workload process — every replica of a
    job shares ONE trace id."""
    from ..api.labels import ANNOTATION_TRACE_CONTEXT
    from ..obs.trace import TRACE_CONTEXT_ENV

    ctx = trace_context_for(job)
    if ctx is None:
        return
    encoded = ctx.encode()
    c.set_env_default(TRACE_CONTEXT_ENV, encoded)
    pod.metadata.annotations = {
        **pod.metadata.annotations,
        ANNOTATION_TRACE_CONTEXT: encoded,
    }


def serving_port(spec: TFReplicaSpec) -> int:
    """The replica's request port: the template's first container port,
    else the serve module default."""
    from ..workloads.serve import DEFAULT_SERVE_PORT

    if spec.template is not None:
        for c in spec.template.spec.containers:
            for p in c.ports:
                if p.container_port:
                    return p.container_port
    return DEFAULT_SERVE_PORT


def _wire_serving_pod(job: TFJob, spec: TFReplicaSpec, pod: Pod,
                      index: int) -> None:
    """Serving replicas are independent long-running servers, never a
    collective: no coordinator wiring: each gets its request port, the
    job's WEIGHTS generation (the gang-generation annotation doubles as
    the rolling-update version — a generation bump rolls every replica,
    one at a time, through graceful drain), and — when the spec pins a
    slice topology — a single-member gang annotation per replica so the
    PR 7 scheduler admits each replica alone onto one slice (warm-pool
    readmission and the shared AOT cache make scale-up cache-hit on
    spawn)."""
    from ..api.labels import ANNOTATION_GANG_GENERATION
    from ..workloads.serve import ENV_SERVE_PORT

    c = pod.spec.containers[0]
    port = serving_port(spec)
    c.set_env_default(ENV_SERVE_PORT, str(port))
    if not any(p.container_port == port for p in c.ports):
        c.ports.append(ContainerPort(name="serve", container_port=port))
    gen = gang_generation(job)
    c.set_env(ENV_GANG_GENERATION, str(gen))
    pod.metadata.annotations = {
        **pod.metadata.annotations,
        ANNOTATION_GANG_GENERATION: str(gen),
    }
    if spec.tpu is not None:
        # One slice per replica, admitted through the scheduler: the gang
        # name is per-INDEX (a width-1 gang), so replicas queue, preempt
        # and warm-readmit independently of each other.
        pod.metadata.annotations.update({
            ANNOTATION_GANG_NAME: f"{gang_name(job)}-serve-{index}",
            ANNOTATION_GANG_SIZE: "1",
            ANNOTATION_ACCELERATOR: spec.tpu.accelerator_type,
            ANNOTATION_NUM_SLICES: "1",
            ANNOTATION_PRIORITY_CLASS: job.spec.priority_class_name
            or "default",
        })
        c.resources.requests[RESOURCE_TPU] = str(spec.tpu.chips_per_host)
        c.resources.limits[RESOURCE_TPU] = str(spec.tpu.chips_per_host)
    if pod.spec.restart_policy == "Always":
        # Crash recovery is the controller's job (index-preserving
        # replacement under the restart policy engine), not the node's.
        pod.spec.restart_policy = "OnFailure"


def _wire_worker_collectives(job: TFJob, pod: Pod, c, index: int) -> None:
    """Give classic Worker replicas the jax.distributed contract too.

    The reference's workers exchange gradients only through the PS grpc
    data plane (ref: mnist_replica.py:137-141); TPU-native, the workers
    themselves form one jax.distributed cluster (coordinator = worker 0's
    service, which already exposes TF_PORT) and all-reduce over XLA
    collectives, training ONE shared model — not N independent shards.
    ``set_env_default`` so a template-provided address (e.g. a test's
    127.0.0.1 override) wins over the generated service DNS name.
    """
    from ..api.labels import ANNOTATION_GANG_GENERATION, ANNOTATION_GANG_NAME

    worker = replica_spec_for(job, ReplicaType.WORKER)
    # Elastic plane: the collective spans the CURRENT width, not the spec
    # width — a degraded gang is a complete (smaller) jax.distributed
    # world, and its data shards rebalance because every member reads the
    # width from here rather than from spec.replicas.
    n = gang_width(job, worker) if worker else 1
    if worker is not None:
        _stamp_elastic(job, worker, pod, c)
    if n <= 1:
        return
    coord = f"{service_name(job, ReplicaType.WORKER, 0)}:{TF_PORT}"
    c.set_env_default(ENV_COORDINATOR, coord)
    c.set_env_default(ENV_NUM_PROCESSES, str(n))
    # Per-pod, never meaningful as a uniform template value: always stamp.
    c.set_env(ENV_PROCESS_ID, str(index))
    # Recovery plane: a multi-process Worker set IS a gang (one failure
    # domain for the collectives it runs) — stamp the gang identity and
    # the controller-bumped generation so replacement gangs rendezvous in
    # a fresh generation namespace and the workload-side guard knows who
    # its peers are.
    gen = gang_generation(job)
    c.set_env(ENV_GANG_GENERATION, str(gen))
    c.set_env(ENV_GANG_NAME_WORKLOAD, gang_name(job))
    pod.metadata.annotations = {
        **pod.metadata.annotations,
        ANNOTATION_GANG_NAME: gang_name(job),
        ANNOTATION_GANG_GENERATION: str(gen),
    }


def _stamp_elastic(job: TFJob, spec: TFReplicaSpec, pod: Pod, c) -> None:
    """Elastic-plane stamps (no-op for non-elastic replica sets): the
    current width for the workload ($KCTPU_GANG_WIDTH + pod annotation)
    and the elastic floor for the scheduler (min-width in pods;
    min-slices on TPU, where harvesting is slice-granular).  Gang
    identity env rides along so even a width-1 degraded survivor knows
    its generation (the re-shard/restore marker needs it)."""
    from ..api.labels import (
        ANNOTATION_ELASTIC_MIN_SLICES,
        ANNOTATION_ELASTIC_MIN_WIDTH,
        ANNOTATION_GANG_WIDTH,
    )
    from ..api.tfjob import elastic_gang_spec

    if elastic_gang_spec(job) is not spec:
        return
    w = gang_width(job, spec)
    c.set_env(ENV_GANG_WIDTH, str(w))
    c.set_env(ENV_GANG_GENERATION, str(gang_generation(job)))
    c.set_env(ENV_GANG_NAME_WORKLOAD, gang_name(job))
    ann = {
        ANNOTATION_GANG_WIDTH: str(w),
        ANNOTATION_ELASTIC_MIN_WIDTH: str(job.spec.elastic.min_width),
    }
    if spec.tf_replica_type == ReplicaType.TPU and spec.tpu is not None:
        per = tpu_slice_hosts(spec.tpu)
        ann[ANNOTATION_ELASTIC_MIN_SLICES] = str(
            max(1, -(-job.spec.elastic.min_width // per)))
    pod.metadata.annotations = {**pod.metadata.annotations, **ann}


def _wire_tpu_pod(job: TFJob, spec: TFReplicaSpec, pod: Pod, index: int) -> None:
    tpu = spec.tpu
    per_slice = tpu_slice_hosts(tpu)
    # Elastic plane: the gang spans its CURRENT width (gang-width
    # annotation) — fewer hosts, proportionally fewer slices.  The spec
    # topology is the full-width shape re-expansion returns to.
    total = gang_width(job, spec)
    slice_idx, local_idx = divmod(index, per_slice)
    coord = f"{coordinator_service_name(job)}:{tpu.coordinator_port}"
    # Per-host DNS via the headless subdomain service: hostname + subdomain
    # resolve as host-<i>.<subdomain>.<ns>.svc (the GKE TPU pattern).
    pod.spec.hostname = f"host-{index}"
    pod.spec.subdomain = service_name(job, ReplicaType.TPU, 0)
    c = pod.spec.containers[0]
    # jax.distributed spans ALL slices: one coordinator, global process ids
    # (ICI within a slice, DCN across — dp across slices is the standard
    # mesh layout, consumed via JobRuntime.num_slices).
    c.set_env(ENV_COORDINATOR, coord)
    c.set_env(ENV_NUM_PROCESSES, str(total))
    c.set_env(ENV_PROCESS_ID, str(index))
    # TPU runtime env is per-slice: worker id and peer hostnames within
    # this pod's slice only (the GKE multislice contract).
    c.set_env(ENV_TPU_WORKER_ID, str(local_idx))
    c.set_env(ENV_TPU_WORKER_HOSTNAMES, ",".join(
        tpu_host_dns(job, i)
        for i in range(slice_idx * per_slice, (slice_idx + 1) * per_slice)
    ))
    c.set_env(ENV_TPU_ACCELERATOR, tpu.accelerator_type)
    # Slice count follows the current width (width changes are
    # slice-granular for TPU gangs — validated at the API).
    num_slices_now = max(1, -(-total // per_slice))
    c.set_env(ENV_NUM_SLICES, str(num_slices_now))
    c.set_env(ENV_SLICE_ID, str(slice_idx))
    # Slice-local coordinator: host 0 of this pod's slice (per-slice
    # rendezvous / rollup), distinct from the global coordinator above.
    c.set_env(ENV_SLICE_COORDINATOR,
              f"{tpu_host_dns(job, slice_idx * per_slice)}:"
              f"{tpu.coordinator_port}")
    # Recovery plane: generation-keyed rendezvous + guard identity.
    from ..api.labels import ANNOTATION_GANG_GENERATION, ANNOTATION_MESH_PP

    gen = gang_generation(job)
    c.set_env(ENV_GANG_GENERATION, str(gen))
    c.set_env(ENV_GANG_NAME_WORKLOAD, gang_name(job))
    # Chip request: never nvidia.com/gpu (BASELINE.json north star).
    c.resources.requests[RESOURCE_TPU] = str(tpu.chips_per_host)
    c.resources.limits[RESOURCE_TPU] = str(tpu.chips_per_host)
    pod.metadata.annotations = {
        **pod.metadata.annotations,
        ANNOTATION_GANG_NAME: gang_name(job),
        ANNOTATION_GANG_SIZE: str(total),
        ANNOTATION_ACCELERATOR: tpu.accelerator_type,
        ANNOTATION_NUM_SLICES: str(num_slices_now),
        ANNOTATION_SLICE_INDEX: str(slice_idx),
        ANNOTATION_PRIORITY_CLASS: job.spec.priority_class_name or "default",
        ANNOTATION_GANG_GENERATION: str(gen),
    }
    if tpu.mesh:
        # Mesh-to-slice plan at the CURRENT width: the workload builds
        # exactly this global mesh (meshmap factors dp over DCN x ICI and
        # pins pp/dp_inter to the slice set the scheduler bound).
        import json

        from .meshmap import plan_mesh_slices

        mplan = plan_mesh_slices(tpu, num_slices_now)
        c.set_env(ENV_MESH, json.dumps(mplan.axes, sort_keys=True))
        pod.metadata.annotations[ANNOTATION_MESH_PP] = str(mplan.pp_span)
    _stamp_elastic(job, spec, pod, c)
    if pod.spec.restart_policy == "Always":
        # A slice process that dies must fail the pod so the whole gang is
        # rescheduled (the slice is the failure domain) — never restart
        # in-place with a torn collective.
        pod.spec.restart_policy = "Never"


def make_service(job: TFJob, spec: TFReplicaSpec, index: int) -> Service:
    typ = spec.tf_replica_type
    svc = Service()
    svc.metadata.name = service_name(job, typ, index)
    svc.metadata.namespace = job.metadata.namespace
    svc.metadata.labels = {**labels_for(job, typ), LABEL_INDEX: str(index)}
    if typ == ReplicaType.TPU:
        # One headless subdomain service for the whole slice: selects every
        # gang pod (no index), clusterIP None so per-pod DNS resolves.
        port = spec.tpu.coordinator_port if spec.tpu else TF_PORT
        svc.spec.selector = labels_for(job, typ)
        svc.spec.cluster_ip = "None"
    elif typ == ReplicaType.SERVING:
        # Per-replica ClusterIP at the request port: the front end routes
        # requests per replica (least-loaded), so each needs its own
        # stable name — exactly the PS/Worker shape at a different port.
        port = serving_port(spec)
        svc.spec.selector = {**labels_for(job, typ), LABEL_INDEX: str(index)}
    else:
        port = TF_PORT
        svc.spec.selector = {**labels_for(job, typ), LABEL_INDEX: str(index)}
    svc.spec.ports = [ServicePort(name="port", port=port, target_port=port)]
    return svc
