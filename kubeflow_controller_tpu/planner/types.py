"""Planner event vocabulary.

Successor of the reference's Event{ActionType, Number} (ref: pkg/tensorflow/
types.go:19-43).  Differences by design: events carry (replica_type, index)
identity instead of bare counts, and deletion is implemented (the reference
declared ActionShouldDelete and never produced or handled it,
types.go:39-40).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass
from typing import List, Optional

from ..api.tfjob import ReplicaType


class Action(str, enum.Enum):
    ADD_POD = "AddPod"
    ADD_SERVICE = "AddService"
    DELETE_POD = "DeletePod"
    DELETE_SERVICE = "DeleteService"
    # Serving plane: mark a Serving pod draining (stop intake -> finish
    # in-flight -> exit) instead of deleting it outright.  Executed as a
    # pod metadata patch; generates a MODIFIED watch event, so it needs no
    # expectations entry (unlike creates/deletes, whose events may never
    # arrive on failure).
    DRAIN_POD = "DrainPod"


@dataclass
class PlanEvent:
    action: Action
    replica_type: ReplicaType
    index: int = 0
    # For deletes: the concrete object name observed in the cluster.
    name: str = ""
    reason: str = ""


@dataclass
class Plan:
    """Ordered event list plus bookkeeping the controller needs up-front."""

    events: List[PlanEvent]
    # Creations/deletions to expect before the next sync (expectations cache).
    creations: int = 0
    deletions: int = 0

    def __post_init__(self):
        self.creations = sum(
            1 for e in self.events if e.action in (Action.ADD_POD, Action.ADD_SERVICE)
        )
        self.deletions = sum(
            1 for e in self.events if e.action in (Action.DELETE_POD, Action.DELETE_SERVICE)
        )

    @property
    def empty(self) -> bool:
        return not self.events
