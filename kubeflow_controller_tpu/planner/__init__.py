"""The planner: computes ordered create/delete events from desired vs observed.

Semantic successor of pkg/tensorflow/ (the reference's "TF domain logic"),
redesigned with the reference's admitted gaps fixed (SURVEY.md §7 step 4):

- templates are deep-copied before per-index mutation (vs the shared-template
  bug at distributed.go:120-128);
- replica identity (type, index) is first-class, so failed replicas are
  replaced **index-preservingly** (vs design_doc.md:228-260 "cannot know
  which task_index died");
- services are diffed per index, so partial service sets are repaired
  (vs the TODO at distributed.go:78-92);
- scale-down and terminal-state cleanup emit delete events (vs the unused
  ActionShouldDelete at types.go:39-40 and the missing PS recycling);
- a TPU replica type materializes gang-annotated pods wired for
  ``jax.distributed`` (net-new, BASELINE.json north star).
"""

from .types import Action, PlanEvent, Plan  # noqa: F401
from .plan import plan_job  # noqa: F401
from .materialize import (  # noqa: F401
    TF_PORT,
    coordinator_service_name,
    make_pod,
    make_service,
    pod_index,
    pods_by_index,
    service_name,
    services_by_index,
)
